"""Fault-tolerance subsystem: deterministic injection, watchdog, retry,
crash-safe checkpoints (ISSUE 1 acceptance suite).

Every scenario runs a seeded FaultPlan; the contract is that each
injected fault is either survived or surfaced as a NAMED diagnostic —
no hangs, no silent corruption — and that replaying the same plan
reproduces the identical failure sequence.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fault_tolerance as ft
from paddle_tpu.distributed.fault_tolerance.plan import (
    FaultPlan, inject, fault_point, InjectedConnectionError,
    SimulatedWorkerDeath)
from paddle_tpu.distributed.store import TCPStore, _PyStoreServer

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# FaultPlan determinism
# ---------------------------------------------------------------------------

def _drive(plan):
    """A fixed call pattern against a plan; returns the fired history."""
    with inject(plan):
        for _ in range(20):
            try:
                fault_point("site.a")
            except InjectedConnectionError:
                pass
            try:
                fault_point("site.b")
            except InjectedConnectionError:
                pass
    return list(plan.history)


def test_fault_plan_seeded_replay_identical():
    mk = lambda: (FaultPlan(seed=1234)
                  .add("site.a", "drop", prob=0.3, count=None)
                  .add("site.b", "drop", after=2, count=3))
    h1, h2 = _drive(mk()), _drive(mk())
    assert h1 == h2                      # identical failure sequence
    assert any(s == "site.a" for s, _, _ in h1)   # prob events fired
    b_hits = [i for s, _, i in h1 if s == "site.b"]
    assert b_hits == [2, 3, 4]           # occurrence-triggered window
    # a different seed produces a different (but still deterministic)
    # probabilistic sequence
    h3 = _drive(FaultPlan(seed=99).add("site.a", "drop", prob=0.3,
                                       count=None))
    assert [x for x in h3] == _drive(
        FaultPlan(seed=99).add("site.a", "drop", prob=0.3, count=None))


def test_fault_plan_env_and_compact_parsing(monkeypatch):
    # compact form
    p = FaultPlan.parse(
        "seed=7;store.connect:drop:count=2;heartbeat.beat:stall:delay=0.01")
    assert p.seed == 7 and len(p.events) == 2
    assert p.events[0].site == "store.connect"
    assert p.events[0].count == 2
    assert p.events[1].delay == pytest.approx(0.01)
    # JSON round-trip
    p2 = FaultPlan.parse(p.to_json())
    assert [e.to_dict() for e in p2.events] == \
        [e.to_dict() for e in p.events]
    # env activation (checked once per process state)
    ft.clear_active_plan()
    monkeypatch.setenv(ft.ENV_FAULT_PLAN, "worker.step:kill:after=1")
    try:
        assert ft.active_plan() is not None
        fault_point("worker.step")  # occurrence 0: below `after`
        with pytest.raises(SimulatedWorkerDeath):
            fault_point("worker.step")
    finally:
        ft.clear_active_plan()
        monkeypatch.delenv(ft.ENV_FAULT_PLAN)
        ft.clear_active_plan()


# ---------------------------------------------------------------------------
# TCPStore: startup race, restart mid-rendezvous, deadlines
# ---------------------------------------------------------------------------

def test_store_connect_backoff_survives_dropped_connects():
    srv = _PyStoreServer(0)
    plan = FaultPlan(seed=0).add("store.connect", "drop", count=3)
    try:
        with inject(plan):
            store = TCPStore("127.0.0.1", srv.port, timeout=15)
        store.set("k", b"v")
        assert store.get("k") == b"v"
        store.close()
        # exactly the 3 scheduled connect drops fired, then recovery
        assert [s for s, _, _ in plan.history] == ["store.connect"] * 3
    finally:
        srv.stop()


def test_store_replays_idempotent_ops_across_restart():
    srv = _PyStoreServer(0)
    port = srv.port
    store = TCPStore("127.0.0.1", port, timeout=10)
    store.set("persist", b"before")
    # hard restart: connections die, data is gone (rendezvous keys are
    # re-published by workers on reconnect in real flows)
    srv.stop()
    srv2 = _PyStoreServer(port)
    try:
        # idempotent query reconnects+replays instead of failing hard
        assert store.query("persist") is None
        writer = TCPStore("127.0.0.1", port, timeout=10)
        writer.set("persist", b"after")
        assert store.get("persist") == b"after"
        writer.close()
        store.close()
    finally:
        srv2.stop()


def test_store_per_op_deadline_names_the_op():
    srv = _PyStoreServer(0)
    try:
        store = TCPStore("127.0.0.1", srv.port, timeout=1)
        with pytest.raises(TimeoutError, match="get"):
            store.get("never_set")       # parks server-side → deadline
        store.close()
    finally:
        srv.stop()


def test_store_nonidempotent_ops_fail_hard_on_drop():
    srv = _PyStoreServer(0)
    try:
        store = TCPStore("127.0.0.1", srv.port, timeout=5)
        with inject(FaultPlan(seed=0).add("store.set", "drop")):
            with pytest.raises(ConnectionError, match="set"):
                store.set("k", b"v")
        store.set("k", b"v2")            # recovered after the fault
        assert store.get("k") == b"v2"
        store.close()
    finally:
        srv.stop()


def test_pystore_server_shutdown_joins_threads():
    srv = _PyStoreServer(0)
    c = TCPStore("127.0.0.1", srv.port, timeout=5)
    c.set("a", b"1")
    c.close()
    srv.stop()
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name.startswith("Thread") and t.is_alive()
                  and ("_accept" in repr(t) or "_serve" in repr(t))]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, leaked
    srv.stop()  # idempotent


# ---------------------------------------------------------------------------
# Collective watchdog
# ---------------------------------------------------------------------------

def test_collective_watchdog_timeout_names_op_and_ranks():
    import paddle_tpu.distributed as dist
    srv = _PyStoreServer(0)
    store = TCPStore("127.0.0.1", srv.port, timeout=5)
    try:
        ft.enable_watchdog(timeout=0.3, store=store, rank=0, world_size=2)
        plan = FaultPlan(seed=0).add("collective.all_reduce", "stall",
                                     delay=2.0)
        t = paddle.to_tensor(np.ones(4, np.float32))
        with inject(plan):
            with pytest.raises(ft.CollectiveTimeoutError) as ei:
                dist.all_reduce(t)
        err = ei.value
        assert err.op == "all_reduce"
        assert err.checked_in == [0]     # this rank entered the op
        assert err.missing == [1]        # the dead peer never did
        assert "all_reduce" in str(err) and "missing: [1]" in str(err)
        # watchdog off → the same op completes untouched
        ft.disable_watchdog()
        dist.all_reduce(t)
    finally:
        ft.disable_watchdog()
        store.close()
        srv.stop()


def test_monitored_barrier_timeout():
    import paddle_tpu.distributed as dist
    try:
        ft.enable_watchdog(timeout=0.2)
        with inject(FaultPlan(seed=0).add("collective.monitored_barrier",
                                          "stall", delay=1.5)):
            with pytest.raises(ft.CollectiveTimeoutError,
                               match="monitored_barrier"):
                dist.monitored_barrier()
    finally:
        ft.disable_watchdog()


def test_watchdog_passthrough_when_disabled():
    import paddle_tpu.distributed as dist
    ft.disable_watchdog()
    t = paddle.to_tensor(np.arange(4, dtype=np.float32))
    out = dist.all_reduce(t)     # nranks==1 identity, no watchdog
    np.testing.assert_allclose(np.asarray(out._value),
                               np.arange(4, dtype=np.float32))


# ---------------------------------------------------------------------------
# Crash-safe checkpoints
# ---------------------------------------------------------------------------

def _state():
    return {"w": paddle.to_tensor(
        np.arange(12, dtype=np.float32).reshape(3, 4))}


def test_checkpoint_manifest_commits_save(tmp_path):
    from paddle_tpu.distributed.checkpoint import save_state_dict
    ck = str(tmp_path / "ck_0")
    save_state_dict(_state(), ck)
    ok, reasons = ft.validate_checkpoint(ck)
    assert ok, reasons
    # no manifest ⇒ incomplete by definition
    os.unlink(os.path.join(ck, "manifest.json"))
    ok, reasons = ft.validate_checkpoint(ck)
    assert not ok and "manifest" in reasons[0]


def test_corrupted_checkpoint_falls_back_to_last_good(tmp_path):
    from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                   load_state_dict)
    root = tmp_path / "ckpts"
    good, bad = str(root / "step_1"), str(root / "step_2")
    st = _state()
    save_state_dict(st, good)
    st["w"] = paddle.to_tensor(np.full((3, 4), 7.0, np.float32))
    save_state_dict(st, bad)
    # torn write after the manifest was cut (worst case: silent rot)
    ft.corrupt_file(os.path.join(bad, "shard_0.pkl"), seed=3)
    ok, reasons = ft.validate_checkpoint(bad)
    assert not ok and "checksum" in reasons[0]
    # no fallback → named diagnostic, never silent garbage
    target = _state()
    with pytest.raises(ft.CheckpointCorruptionError, match="step_2"):
        load_state_dict(target, bad)
    # with fallback → newest valid sibling wins
    target = {"w": paddle.to_tensor(np.zeros((3, 4), np.float32))}
    with pytest.warns(RuntimeWarning, match="falling back"):
        load_state_dict(target, bad, fallback_path=str(root))
    np.testing.assert_allclose(
        np.asarray(target["w"]._value),
        np.arange(12, dtype=np.float32).reshape(3, 4))


def test_checkpoint_corrupt_injection_site(tmp_path):
    from paddle_tpu.distributed.checkpoint import save_state_dict
    ck = str(tmp_path / "ck")
    plan = FaultPlan(seed=5).add("checkpoint.commit", "corrupt")
    with inject(plan):
        save_state_dict(_state(), ck)
    assert plan.history == [("checkpoint.commit", "corrupt", 0)]
    ok, reasons = ft.validate_checkpoint(ck)
    assert not ok                        # the manifest catches the rot


def test_checkpoint_killed_mid_save_is_visibly_incomplete(tmp_path):
    from paddle_tpu.distributed.checkpoint import save_state_dict
    ck = str(tmp_path / "ck")
    with inject(FaultPlan(seed=0).add("checkpoint.write", "kill")):
        with pytest.raises(SimulatedWorkerDeath):
            save_state_dict(_state(), ck)
    ok, reasons = ft.validate_checkpoint(ck)
    assert not ok and "manifest" in reasons[0]   # never committed


def test_elastic_manager_resume_checkpoint(tmp_path):
    from paddle_tpu.distributed.checkpoint import save_state_dict
    from paddle_tpu.distributed.fleet.elastic.manager import (
        ElasticManager, ElasticStore)
    root = tmp_path / "ckpts"
    g1, g2 = str(root / "gen_1"), str(root / "gen_2")
    save_state_dict(_state(), g1)
    save_state_dict(_state(), g2)
    mgr = ElasticManager(rank=0, world_size=1,
                         store=ElasticStore(path=str(tmp_path / "es")))
    assert mgr.record_checkpoint(g2, step=20)
    assert mgr.resume_checkpoint() == (g2, 20)
    # the recorded generation rots between record and relaunch →
    # resume falls back to the previous good generation
    ft.corrupt_file(os.path.join(g2, "shard_0.pkl"))
    path, _ = mgr.resume_checkpoint()
    assert path == g1
    # recording an invalid checkpoint is refused outright
    assert not mgr.record_checkpoint(str(root / "nonexistent"))


# ---------------------------------------------------------------------------
# Heartbeats: monotonic staleness + stall injection
# ---------------------------------------------------------------------------

def test_heartbeat_immune_to_wall_clock_jump(tmp_path, monkeypatch):
    from paddle_tpu.distributed.fleet.elastic.manager import (
        ElasticManager, ElasticStore)
    store = ElasticStore(path=str(tmp_path))
    writer = ElasticManager(rank=0, world_size=1, timeout=0.4,
                            interval=0.1, store=store)
    watcher = ElasticManager(rank=0, world_size=1, timeout=0.4,
                             interval=0.1, store=store)
    # the writer's wall clock jumps a year into the future mid-run —
    # the wall-clock-delta scheme would mask this rank's later death
    # (now - beat < 0) and flag healthy ranks dead elsewhere
    writer.beat()
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() + 3.15e7)
    writer.beat()
    assert watcher.dead_ranks() == []    # beating → alive, jump ignored
    monkeypatch.setattr(time, "time", real_time)
    # now the rank goes silent: staleness must still fire, judged on
    # the watcher's monotonic clock, not the poisoned wall stamps
    time.sleep(0.6)
    assert watcher.dead_ranks() == [0]


def test_heartbeat_stall_injection_detected(tmp_path):
    from paddle_tpu.distributed.fleet.elastic.manager import (
        ElasticManager, ElasticStore)
    store = ElasticStore(path=str(tmp_path))
    writer = ElasticManager(rank=0, world_size=1, timeout=0.3,
                            interval=0.05, store=store)
    watcher = ElasticManager(rank=0, world_size=1, timeout=0.3,
                             interval=0.05, store=store)
    plan = FaultPlan(seed=0).add("heartbeat.beat", "drop", after=1,
                                 count=None)
    with inject(plan):
        writer.start()                   # first beat lands, rest drop
        time.sleep(0.1)
        assert watcher.dead_ranks() == []
        time.sleep(0.6)
        dead = watcher.dead_ranks()
        writer.stop()
    assert dead == [0]                   # silenced rank was detected
    assert plan.history[0][0] == "heartbeat.beat"


# ---------------------------------------------------------------------------
# NaN gradients: poisoning + skip-step sentinel
# ---------------------------------------------------------------------------

def _sgd_fixture():
    from paddle_tpu import nn, optimizer
    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype(np.float32))
    loss = m(x).sum()
    loss.backward()
    return m, opt


def test_nan_poison_injection_then_skip_step():
    from paddle_tpu.amp import debugging
    m, opt = _sgd_fixture()
    before = np.asarray(m.weight._value).copy()
    plan = FaultPlan(seed=0).add("grad.poison", "nan")
    with inject(plan):
        skipped = debugging.skip_step_on_nonfinite(opt)
    assert plan.history == [("grad.poison", "nan", 0)]
    assert skipped                       # sentinel caught the poison
    np.testing.assert_array_equal(np.asarray(m.weight._value), before)
    rep = debugging.last_nonfinite()
    assert rep is not None and rep["kind"] == "nan"
    assert rep["var_name"]               # names the offending tensor


def test_skip_step_applies_clean_gradients():
    from paddle_tpu.amp import debugging
    m, opt = _sgd_fixture()
    before = np.asarray(m.weight._value).copy()
    skipped = debugging.skip_step_on_nonfinite(opt)
    assert not skipped
    assert not np.allclose(np.asarray(m.weight._value), before)


def test_grad_poison_without_sentinel_corrupts_update():
    """Sanity: the fault is real — an unprotected optimizer.step()
    propagates the poison into the weights."""
    m, opt = _sgd_fixture()
    with inject(FaultPlan(seed=0).add("grad.poison", "nan")):
        opt.step()
    assert np.isnan(np.asarray(m.weight._value)).any()


def test_check_numerics_names_tensor_and_op():
    from paddle_tpu.amp import debugging
    bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
    with pytest.raises(debugging.NonFiniteError,
                       match="matmul:layer0.w"):
        debugging.check_numerics(bad, op_type="matmul",
                                 var_name="layer0.w")
    has_nan, has_inf = debugging.check_numerics(
        bad, op_type="matmul", var_name="layer0.w",
        debug_mode=debugging.DebugMode.CHECK_NAN_INF)
    assert bool(np.asarray(has_nan._value))
    assert not bool(np.asarray(has_inf._value))


# ---------------------------------------------------------------------------
# Retry/backoff primitives
# ---------------------------------------------------------------------------

def test_backoff_deterministic_jitter():
    a = [next(d) for d in [ft.backoff_delays(seed=11)] for _ in range(6)]
    b = [next(d) for d in [ft.backoff_delays(seed=11)] for _ in range(6)]
    assert a == b
    assert a != [next(d) for d in [ft.backoff_delays(seed=12)]
                 for _ in range(6)]
    assert all(x <= 2.0 * 1.25 for x in a)   # max_delay * max jitter


def test_retry_call_bounded():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert ft.retry_call(flaky, retries=3, base=0.001) == "ok"
    assert len(calls) == 3
    with pytest.raises(ft.RetryExhausted):
        ft.retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                      retries=1, base=0.001)
