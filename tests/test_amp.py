import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, amp
import paddle_tpu.nn.functional as F


def test_auto_cast_o1_dtypes():
    lin = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
        y = lin(x)
        assert y.dtype == paddle.bfloat16
        # black-list op stays f32
        s = paddle.nn.functional.softmax(y)
        assert s.dtype == paddle.float32
    y2 = lin(x)
    assert y2.dtype == paddle.float32


def test_auto_cast_disabled():
    lin = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    with paddle.amp.auto_cast(enable=False, dtype="bfloat16"):
        y = lin(x)
    assert y.dtype == paddle.float32


def test_amp_training_bf16_converges():
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=model.parameters())
    rng = np.random.RandomState(0)
    xv = rng.rand(32, 8).astype(np.float32)
    yv = (xv @ rng.rand(8, 1)).astype(np.float32)
    x, y = paddle.to_tensor(xv), paddle.to_tensor(yv)
    losses = []
    for _ in range(30):
        with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
            pred = model(x)
            loss = F.mse_loss(pred.astype("float32"), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.5
    # master params stay f32
    assert model[0].weight.dtype == paddle.float32


def test_grad_scaler_fp16_flow():
    model = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    x = paddle.randn([4, 4])
    with paddle.amp.auto_cast(dtype="float16", level="O1"):
        loss = model(x).astype("float32").sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()
    assert np.isfinite(model.weight.numpy()).all()


def test_grad_scaler_inf_skips_step():
    model = nn.Linear(2, 2)
    w_before = model.weight.numpy().copy()
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 15)
    x = paddle.to_tensor(np.asarray([[3e38, 3e38]], np.float32))
    loss = model(x).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)  # grads overflow → step skipped
    np.testing.assert_allclose(model.weight.numpy(), w_before)
    assert scaler._scale < 2.0 ** 15  # scale decreased


def test_amp_decorate_o2():
    model = nn.Linear(4, 4)
    model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    assert model.weight.dtype == paddle.bfloat16


def test_amp_conv_backward_bf16():
    """Regression: conv under autocast used preferred_element_type=f32 +
    astype, whose transpose rule mixes an f32 cotangent with the bf16
    weight and raises inside lax.conv_general_dilated (r4)."""
    paddle.seed(0)
    conv = nn.Conv2D(3, 8, 3)
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((2, 3, 16, 16))
        .astype(np.float32))
    with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
        h = conv(x)
    assert h.dtype == paddle.bfloat16
    loss = h.sum()
    loss.backward()
    assert conv.weight.grad is not None
    assert np.isfinite(conv.weight.grad.numpy().astype(np.float32)).all()


def test_amp_conv_transpose_backward_bf16():
    paddle.seed(0)
    conv = nn.Conv2DTranspose(3, 8, 3)
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((2, 3, 8, 8))
        .astype(np.float32))
    with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
        h = conv(x)
    loss = h.sum()
    loss.backward()
    assert conv.weight.grad is not None


def test_static_auto_cast_records_bf16_casts():
    """auto_cast inside program_guard must actually rewrite dtypes:
    round-5 found the static hook consuming ops before the AMP caster
    ran, silently building all-f32 'AMP' programs."""
    import jax
    import numpy as np
    from paddle_tpu import static, optimizer

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            y = static.data("y", [4, 1], "float32")
            lin = paddle.nn.Linear(8, 1)
            with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
                loss = paddle.nn.functional.mse_loss(lin(x), y)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=main.all_parameters())
            opt.minimize(loss)
        exe = static.Executor()
        fd = {"x": np.ones((4, 8), np.float32),
              "y": np.ones((4, 1), np.float32)}
        call, _ = exe._prologue(main, fd, [loss], 0)
        entry, fv, pv, ov, rv, lr, st = call
        aval = lambda t: jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), t)
        txt = jax.jit(entry["pure"]).lower(
            aval(fv), aval(pv), aval(ov), aval(rv),
            jax.ShapeDtypeStruct((), np.float32),
            jax.ShapeDtypeStruct((), np.int32)).as_text()
        assert "bf16" in txt, "static auto_cast(bfloat16) produced no bf16"
        # and the compiled step still trains
        (l0,) = exe.run(main, feed=fd, fetch_list=[loss])
        for _ in range(5):
            (l1,) = exe.run(main, feed=fd, fetch_list=[loss])
        assert float(l1) < float(l0)
    finally:
        paddle.disable_static()


def test_rewrite_program_bf16_post_hoc_pass():
    """static.amp.bf16.rewrite_program_bf16: cast insertion over a
    program built WITHOUT autocast — white ops get bf16 inputs, the
    step still trains, grads stay f32 on the params."""
    import jax
    import numpy as np
    from paddle_tpu import static, optimizer
    from paddle_tpu.static import amp as samp

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [8, 16], "float32")
            y = static.data("y", [8, 1], "float32")
            h = paddle.nn.Linear(16, 32)(x)
            h = paddle.nn.functional.relu(h)
            pred = paddle.nn.Linear(32, 1)(h)
            loss = paddle.nn.functional.mse_loss(pred, y)
        n_ops = len(main.global_block().ops)
        samp.bf16.rewrite_program_bf16(main)
        assert len(main.global_block().ops) > n_ops, "no casts inserted"
        with static.program_guard(main):
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=main.all_parameters())
            opt.minimize(loss)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        fd = {"x": rng.rand(8, 16).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)}
        call, _ = exe._prologue(main, fd, [loss], 0)
        entry, fv, pv, ov, rv, lr, st = call
        aval = lambda t: jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), t)
        txt = jax.jit(entry["pure"]).lower(
            aval(fv), aval(pv), aval(ov), aval(rv),
            jax.ShapeDtypeStruct((), np.float32),
            jax.ShapeDtypeStruct((), np.int32)).as_text()
        assert "bf16" in txt, "rewrite produced no bf16"
        losses = [float(exe.run(main, feed=fd, fetch_list=[loss])[0])
                  for _ in range(30)]
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
        for p in main.all_parameters():  # params stayed f32 (O1 rewrite)
            assert p._value.dtype == np.float32
    finally:
        paddle.disable_static()


def test_rewrite_program_bf16_restores_f32_for_black_ops():
    """A black op downstream of a white op must get an f32 cast-back:
    the pass tracks EFFECTIVE dtypes (build-time avals go stale as it
    retargets), otherwise softmax/norm silently run in bf16."""
    import jax.numpy as jnp
    from paddle_tpu import static
    from paddle_tpu.static import amp as samp

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            h = paddle.matmul(x, paddle.to_tensor(
                np.ones((8, 8), np.float32)))      # white
            s = paddle.nn.functional.softmax(h)    # black
        samp.bf16.rewrite_program_bf16(main)
        ops = main.global_block().ops
        sm = next(o for o in ops if o.type == "softmax")
        casts_to_f32 = [o for o in ops if o.type == "cast"
                        and any(o.outputs[0] is i for i in sm.inputs)
                        and o.outputs[0]._value.dtype == jnp.float32]
        assert casts_to_f32, (
            "softmax input not cast back to f32 after a white matmul")
        exe = static.Executor()
        (out,) = exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                         fetch_list=[s])
        np.testing.assert_allclose(np.asarray(out).sum(), 4.0, rtol=1e-5)
    finally:
        paddle.disable_static()
