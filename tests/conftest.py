"""Test env: force XLA-CPU with 8 virtual devices BEFORE jax import.

This is the fake-device strategy from SURVEY.md §4: the reference tests
distributed code with Gloo/custom-device fakes on localhost; here an
8-device CPU mesh exercises the same sharding/collective paths the TPU
uses.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
