"""Test env: force XLA-CPU with 8 virtual devices BEFORE jax import.

This is the fake-device strategy from SURVEY.md §4: the reference tests
distributed code with Gloo/custom-device fakes on localhost; here an
8-device CPU mesh exercises the same sharding/collective paths the TPU
uses.

IMPORTANT (this environment): a sitecustomize registers an out-of-process
TPU PJRT plugin and calls ``jax.config.update("jax_platforms",
"axon,cpu")`` at interpreter start, which overrides the JAX_PLATFORMS env
var and makes the first backend lookup block on the TPU tunnel (observed
>9 min). Resetting the config value after importing jax — before any
backend is initialized — restores a fast pure-CPU test run.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The ambient environment exports JAX_PLATFORMS=axon for every process,
# so that env var can't distinguish "driver default" from "developer
# explicitly wants hardware".  PADDLE_TPU_TEST_REAL=1 is the explicit
# opt-in for running the suite on the TPU; otherwise reset to CPU so the
# sitecustomize's "axon,cpu" override can't stall the suite on the
# tunnel.
if os.environ.get("PADDLE_TPU_TEST_REAL") != "1":
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

# NOTE: do NOT point the persistent XLA compile cache
# (PADDLE_TPU_COMPILE_CACHE_DIR) at the whole suite from here.  It
# looks like a free ~100s: the module-boundary clear_caches() below
# forces structurally shared programs (the serving engine alone is
# compiled by four separate test modules) to recompile, and the disk
# cache would serve those as content-addressed hits.  But on this
# jaxlib (0.4.37, CPU backend) DESERIALIZING a multi-device SPMD
# executable from the cache segfaults the process (reproduced:
# test_fleet.py::test_pipeline_parallel_loss_parity crashes in
# pxla.__call__ on a warm cache).  Single-device opt-in via the env
# var still works for bench/executor paths.
import gc  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _drop_jax_caches_between_modules():
    """The full suite compiles hundreds of XLA CPU executables; letting
    them accumulate has intermittently aborted (SIGABRT) late heavy
    tests (observed: llama backward in test_models).  Dropping compiled
    caches at module boundaries keeps the process footprint flat."""
    yield
    try:
        jax.clear_caches()
    except Exception:
        pass
    gc.collect()
