"""Persistent XLA compilation cache wiring.

``PADDLE_TPU_COMPILE_CACHE_DIR`` points JAX's on-disk compilation cache
at a directory; every ``jax.jit(...).lower(...).compile()`` in the
process (the static Executor, ``run_steps`` fused loops, ``jit.
to_static``, eager segment compiles) then writes its executable there
and warm-process compiles are served from disk — measured ~3.5x faster
on CPU, far larger on TPU where Mosaic/XLA compiles are minutes-class.

The in-process layer above it is the Executor's program-fingerprint
-keyed executable cache (``static/executor.py``): a structurally
identical (program, feed-spec, fetch-spec) triple reuses the compiled
entry across Executor instances without even re-lowering.

``ensure_compile_cache()`` is called lazily right before the first
compile; it is idempotent and near-free after the first call.  Every
compile site records ``compile.count`` / ``compile.ms`` in the
observability metrics registry so cold vs warm compile cost is
measurable (bench.py reports both).
"""
from __future__ import annotations

import os
import threading

__all__ = ["ENV_COMPILE_CACHE_DIR", "ensure_compile_cache",
           "compile_cache_dir", "compile_cache_enabled",
           "record_compile_metrics"]

ENV_COMPILE_CACHE_DIR = "PADDLE_TPU_COMPILE_CACHE_DIR"

_lock = threading.Lock()
_configured_dir = None  # the dir last applied (None = not applied)
_probed = False


def compile_cache_dir():
    """The configured cache directory, or None when disabled."""
    d = os.environ.get(ENV_COMPILE_CACHE_DIR, "").strip()
    return d or None


def compile_cache_enabled():
    return _configured_dir is not None


def ensure_compile_cache():
    """Apply ``PADDLE_TPU_COMPILE_CACHE_DIR`` to JAX's persistent
    compilation cache (idempotent; re-applies if the env var changed).

    Thresholds are zeroed so even fast CPU-test compiles persist —
    the default min-compile-time gate would skip exactly the programs
    the test suite and bench CPU path exercise.  Returns the active
    cache dir or None.
    """
    global _configured_dir, _probed
    d = compile_cache_dir()
    if d == _configured_dir and _probed:
        return _configured_dir
    with _lock:
        d = compile_cache_dir()
        if d == _configured_dir and _probed:
            return _configured_dir
        _probed = True
        if d is None:
            if _configured_dir is not None:
                try:
                    import jax
                    jax.config.update("jax_compilation_cache_dir", None)
                    from jax._src import compilation_cache as _jcc
                    _jcc.reset_cache()
                except Exception:
                    pass
            _configured_dir = None
            return None
        try:
            import jax
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            try:
                # jax's disk cache is initialized once, on the first
                # compile — a compile that ran before the dir was set
                # latches it off, so force re-initialization
                from jax._src import compilation_cache as _jcc
                _jcc.reset_cache()
            except Exception:
                pass
            _configured_dir = d
        except Exception:
            # an old jaxlib without the knobs must not break compiles
            _configured_dir = None
    return _configured_dir


def record_compile_metrics(ms, kind="compile"):
    """Land one compile's wall time in the metrics registry
    (``compile.count`` counter + ``compile.ms`` histogram, plus a
    per-kind histogram) — bench.py snapshots these for the cold/warm
    compile report."""
    from .. import observability as obs
    reg = obs.get_registry()
    reg.counter("compile.count").inc()
    reg.histogram("compile.ms").observe(ms)
    reg.histogram(f"compile.ms.{kind}").observe(ms)
