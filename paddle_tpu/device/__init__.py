"""paddle.device parity: set_device, streams/events shims, tpu namespace.

Reference parity: `python/paddle/device/` (incl. `cuda/` streams, events,
empty_cache) [UNVERIFIED — empty reference mount].  TPU-native: PJRT owns
streams/ordering; Stream/Event are functional no-op shims that preserve the
API (synchronize maps to blocking on the last dispatched value).
"""
from __future__ import annotations

import contextlib as _contextlib

import jax

from ..core.place import (set_device, get_device, device_count,
                          is_compiled_with_cuda, current_place, CPUPlace,
                          TPUPlace, CUDAPlace)
from .compile_cache import (ENV_COMPILE_CACHE_DIR, compile_cache_dir,
                            compile_cache_enabled, ensure_compile_cache)

__all__ = ["set_device", "get_device", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cuda",
           "device_count", "synchronize", "Stream", "Event",
           "current_stream", "stream_guard", "get_all_device_type",
           "get_all_custom_device_type", "XPUPlace", "cuda", "tpu", "Place",
           "ENV_COMPILE_CACHE_DIR", "ensure_compile_cache",
           "compile_cache_dir", "compile_cache_enabled"]

Place = TPUPlace
XPUPlace = TPUPlace


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def get_all_device_type():
    return ["cpu", jax.default_backend()]


def get_all_custom_device_type():
    return []


def synchronize(device=None):
    """Block until all dispatched device work completes."""
    try:
        from ..core.pipeline import drain
        drain()  # in-flight pipelined steps synchronize first
    except Exception:
        pass
    try:
        jax.block_until_ready(
            jax.device_put(0, jax.devices()[0]))
        # effectively a fence: jax work is serialized per-device
        (jax.numpy.zeros(()) + 0).block_until_ready()
    except Exception:
        pass


class Stream:
    """PJRT orders work per device; explicit streams are identity shims."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        synchronize()

    def query(self):
        return True


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()


class _CudaNamespace:
    """paddle.device.cuda compat — maps onto the TPU accelerator."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _current_stream

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def empty_cache():
        # XLA/PJRT manages HBM via its own allocator; provide the hook
        import gc
        gc.collect()

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_allocated(device)

    @staticmethod
    def get_device_properties(device=None):
        class Props:
            name = jax.devices()[0].device_kind
            major, minor = 0, 0
            total_memory = 0
            multi_processor_count = 1
        return Props()

    @staticmethod
    def get_device_name(device=None):
        return jax.devices()[0].device_kind

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)


cuda = _CudaNamespace()
tpu = _CudaNamespace()


# ---------------------------------------------------------------------
# HBM observability (SURVEY.md:101: allocator stats /
# fraction_of_gpu_memory_to_use / empty_cache analogues).  PJRT exposes
# per-device allocator counters; these module-level APIs surface them so
# big configs are not tuned blind (VERDICT r3 missing #6).
# ---------------------------------------------------------------------
def memory_stats(device=None):
    """Raw PJRT allocator counters for one device (bytes_in_use,
    peak_bytes_in_use, largest_alloc_size, bytes_limit, ...)."""
    try:
        idx = 0
        if isinstance(device, str) and ":" in device:
            idx = int(device.rsplit(":", 1)[1])
        elif isinstance(device, int):
            idx = device
        return dict(jax.devices()[idx].memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None):
    return memory_stats(device).get("bytes_in_use", 0)


def max_memory_allocated(device=None):
    return memory_stats(device).get("peak_bytes_in_use", 0)


def max_memory_reserved(device=None):
    s = memory_stats(device)
    return s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0))


def memory_summary(device=None):
    """Human-readable allocator summary (the reference's
    memory_summary / allocator stats dump)."""
    s = memory_stats(device)
    if not s:
        return "device memory stats unavailable on this backend"
    gb = 2.0 ** 30
    lines = ["device memory summary:"]
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size", "bytes_reserved",
                "peak_bytes_reserved"):
        if key in s:
            lines.append(f"  {key:<22} {s[key]/gb:8.3f} GiB")
    for k, v in sorted(s.items()):
        if k.startswith("num_"):
            lines.append(f"  {k:<22} {v}")
    return "\n".join(lines)


def empty_cache():
    _CudaNamespace.empty_cache()


@_contextlib.contextmanager
def hbm_oom_context(program="<program>", estimate=None, site="exec.oom"):
    """Re-raise XLA RESOURCE_EXHAUSTED structured (the reference prints
    allocator stats on CUDA OOM).

    Delegates to the memory guard: the body runs under the injectable
    ``exec.oom`` fault site and allocator failures re-raise as
    ``memory.TpuOutOfMemoryError`` carrying the pre-flight estimate (the
    caller's, or the thread's last one), live ``memory_stats()``, and
    remediation hints.  Non-OOM errors pass through untouched."""
    from ..memory.guard import oom_context
    with oom_context(program=program, estimate=estimate, site=site):
        yield
