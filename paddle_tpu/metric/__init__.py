"""paddle.metric parity: Metric base, Accuracy, Precision, Recall, Auc.

Reference parity: `python/paddle/metric/metrics.py` [UNVERIFIED — empty
reference mount].
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (paddle.metric.accuracy)."""
    from ..ops.manipulation import topk as _topk

    probs = np.asarray(input._value)
    labels = np.asarray(label._value)
    if labels.ndim == probs.ndim:
        labels = labels.reshape(labels.shape[:-1])
    idx = np.argsort(-probs, axis=-1)[..., :k]
    correct_mask = (idx == labels[..., None]).any(axis=-1)
    return to_tensor(np.asarray(correct_mask.mean(), np.float32))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._value) if isinstance(pred, Tensor) else \
            np.asarray(pred)
        label_np = np.asarray(label._value) if isinstance(label, Tensor) \
            else np.asarray(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = (idx == label_np[..., None])
        return to_tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        arr = np.asarray(correct._value) if isinstance(correct, Tensor) \
            else np.asarray(correct)
        num = arr.shape[0] if arr.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            c = arr[..., :k].sum()
            self.total[i] += float(c)
            self.count[i] += int(np.prod(arr.shape[:-1]))
            accs.append(self.total[i] / max(self.count[i], 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value) if isinstance(preds, Tensor) else \
            np.asarray(preds)
        l = np.asarray(labels._value) if isinstance(labels, Tensor) else \
            np.asarray(labels)
        p = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value) if isinstance(preds, Tensor) else \
            np.asarray(preds)
        l = np.asarray(labels._value) if isinstance(labels, Tensor) else \
            np.asarray(labels)
        p = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value) if isinstance(preds, Tensor) else \
            np.asarray(preds)
        l = np.asarray(labels._value) if isinstance(labels, Tensor) else \
            np.asarray(labels)
        if p.ndim == 2:
            p = p[:, 1]
        l = l.reshape(-1)
        bins = (p * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from high threshold down
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
