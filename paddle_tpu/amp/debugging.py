"""paddle.amp.debugging parity shims (op stats / nan-inf checks)."""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "check_numerics", "enable_tensor_checker",
           "disable_tensor_checker", "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


_collecting = {"on": False, "stats": {}}


def enable_operator_stats_collection():
    _collecting["on"] = True
    _collecting["stats"] = {}


def disable_operator_stats_collection():
    _collecting["on"] = False


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    arr = np.asarray(tensor._value, np.float32)
    has_nan = bool(np.isnan(arr).any())
    has_inf = bool(np.isinf(arr).any())
    if (has_nan or has_inf) and \
            debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"nan/inf detected in {op_type}:{var_name}")
    from ..core.tensor import to_tensor
    return to_tensor(has_nan), to_tensor(has_inf)


def enable_tensor_checker(config=None):
    pass


def disable_tensor_checker():
    pass
