"""paddle.amp.debugging parity shims (op stats / nan-inf checks).

Robustness extensions (fault_tolerance layer): nonfinite checks report
the FIRST offending tensor by name/op instead of a bare boolean, the
last report is kept for post-mortem (``last_nonfinite()``), and
``skip_step_on_nonfinite`` is the shared skip-step hook — GradScaler,
bare optimizers, and the collective watchdog all route through the same
sentinel so "NaN gradient ⇒ skip the update, keep training" behaves
identically everywhere.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "check_numerics", "enable_tensor_checker",
           "disable_tensor_checker", "DebugMode", "NonFiniteError",
           "first_nonfinite", "last_nonfinite", "skip_step_on_nonfinite"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class NonFiniteError(FloatingPointError):
    """NaN/Inf detected; names the offending tensor and producing op."""

    def __init__(self, var_name="", op_type="", kind="nan/inf"):
        self.var_name = var_name
        self.op_type = op_type
        self.kind = kind
        where = ":".join(p for p in (op_type, var_name) if p) or "<tensor>"
        super().__init__(f"{kind} detected in {where}")


_collecting = {"on": False, "stats": {}}
_last_nonfinite = {"report": None}


def enable_operator_stats_collection():
    _collecting["on"] = True
    _collecting["stats"] = {}


def disable_operator_stats_collection():
    _collecting["on"] = False


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def _kind_of(arr):
    """'nan', 'inf', 'nan/inf' or None for a host array."""
    has_nan = bool(np.isnan(arr).any())
    has_inf = bool(np.isinf(arr).any())
    if has_nan and has_inf:
        return "nan/inf"
    if has_nan:
        return "nan"
    if has_inf:
        return "inf"
    return None


def _record(var_name, op_type, kind):
    report = {"var_name": var_name, "op_type": op_type, "kind": kind}
    _last_nonfinite["report"] = report
    from .. import observability as obs
    obs.instant("amp.nonfinite", cat="amp", var_name=var_name,
                op_type=op_type, kind=kind)
    return report


def last_nonfinite():
    """The most recent nonfinite report ({var_name, op_type, kind}) or
    None — the watchdog/elastic layers read this for diagnostics."""
    return _last_nonfinite["report"]


def first_nonfinite(named_tensors):
    """Scan ``named_tensors`` (dict name->Tensor, or iterable of
    (name, tensor)) and return the FIRST offending report, else None."""
    items = named_tensors.items() if hasattr(named_tensors, "items") \
        else named_tensors
    for name, t in items:
        if t is None:
            continue
        arr = np.asarray(getattr(t, "_value", t), np.float32)
        kind = _kind_of(arr)
        if kind is not None:
            return _record(name, "", kind)
    return None


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    arr = np.asarray(tensor._value, np.float32)
    kind = _kind_of(arr)
    has_nan = kind in ("nan", "nan/inf")
    has_inf = kind in ("inf", "nan/inf")
    if kind is not None:
        _record(var_name, op_type, kind)
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise NonFiniteError(var_name, op_type, kind)
    from ..core.tensor import to_tensor
    return to_tensor(has_nan), to_tensor(has_inf)


def skip_step_on_nonfinite(optimizer, named_grads=None):
    """NaN sentinel → optimizer skip-step (the shared hook).

    Checks the gradients about to be applied (``named_grads`` overrides;
    default: the optimizer's params-with-grad).  If any is nonfinite,
    records the first offending name (``last_nonfinite()``), does NOT
    step, and returns True; otherwise steps and returns False.
    """
    if named_grads is None:
        from ..optimizer.optimizer import run_pre_step_hooks
        params = optimizer._params_with_grad()
        # run the pre-step hooks HERE so injected faults (grad.poison)
        # land before the check; step() below won't re-run them
        run_pre_step_hooks(optimizer, params)
        named_grads = [(p.name or f"param_{i}", p.grad)
                       for i, p in enumerate(params)]
    report = first_nonfinite(named_grads)
    if report is not None:
        # not stepping: clear the hooks-already-ran latch so the next
        # independent step() runs its hooks normally
        from ..optimizer import optimizer as _opt
        _opt._hooks_ran.flag = False
        return True
    optimizer.step()
    return False


def enable_tensor_checker(config=None):
    pass


def disable_tensor_checker():
    pass
