"""paddle.amp: auto_cast + GradScaler.

Reference parity: `python/paddle/amp/auto_cast.py` (O1/O2 white/black op
lists), `grad_scaler.py` [UNVERIFIED — empty reference mount].

TPU-native: bf16 is the native AMP dtype (MXU computes bf16 with f32
accumulation); no loss scaling is needed for bf16, but GradScaler implements
real fp16 dynamic scaling for parity.  The caster installs on the dispatch
path exactly where Paddle's generated AMP branch sits in `*_ad_func`.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import get_dispatch_state
from ..core.dtypes import to_jax_dtype
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "white_list", "black_list", "is_float16_supported",
           "is_bfloat16_supported"]

# O1 white/black lists are GENERATED from ops.yaml (the single source
# of truth for op classification — python -m paddle_tpu.ops.gen);
# matmul-class ops cast to low precision, numerically-sensitive ops
# stay f32.
from ..ops._generated import (AMP_WHITE_LIST as WHITE_LIST,
                              AMP_BLACK_LIST as BLACK_LIST)


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class _AmpState:
    def __init__(self, enable, dtype, level):
        self.enable = enable
        self.dtype = to_jax_dtype(dtype)
        self.level = level


_amp_stack = []


def amp_state():
    return _amp_stack[-1] if _amp_stack else None


def _cast_tensor(t, dtype):
    if not isinstance(t, Tensor):
        return t
    if not jnp.issubdtype(t._value.dtype, jnp.floating):
        return t
    if t._value.dtype == dtype:
        return t
    from ..ops.manipulation import cast
    from ..core.dtypes import to_paddle_dtype
    return cast(t, to_paddle_dtype(dtype))


def _amp_caster(op_name, args):
    st = amp_state()
    if st is None or not st.enable:
        return args
    if op_name == "cast":
        # never rewrite cast's own input: _cast_tensor dispatches
        # "cast", so casting it again recurses forever
        return args
    if st.level == "O2":
        # cast everything except black list
        if op_name in BLACK_LIST:
            target = jnp.float32
        else:
            target = st.dtype
        return tuple(_cast_tensor(a, target) for a in args)
    # O1: white list → low precision; black list → f32; else leave
    if op_name in WHITE_LIST:
        return tuple(_cast_tensor(a, st.dtype) for a in args)
    if op_name in BLACK_LIST:
        return tuple(_cast_tensor(a, jnp.float32) for a in args)
    return args


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="float16", use_promote=True):
    global WHITE_LIST, BLACK_LIST
    saved_w, saved_b = WHITE_LIST, BLACK_LIST
    # REBIND, never mutate: the sets are shared with ops._generated
    # (the yaml-codegen source of truth) — in-place |=/-= would corrupt
    # the generated classification for every other consumer
    if custom_white_list:
        WHITE_LIST = (WHITE_LIST | set(custom_white_list))
        BLACK_LIST = (BLACK_LIST - set(custom_white_list))
    if custom_black_list:
        BLACK_LIST = (BLACK_LIST | set(custom_black_list))
        WHITE_LIST = (WHITE_LIST - set(custom_black_list))
    st = _AmpState(enable, dtype, level)
    _amp_stack.append(st)
    ds = get_dispatch_state()
    prev = ds.amp_caster
    ds.amp_caster = _amp_caster
    try:
        yield
    finally:
        _amp_stack.pop()
        ds.amp_caster = prev if _amp_stack else None
        WHITE_LIST, BLACK_LIST = saved_w, saved_b


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None, **kwargs):
    """paddle.amp.decorate — O2 casts model params to the AMP dtype and
    keeps f32 master weights in the optimizer (which our optimizers do
    automatically: accumulators and the update math are f32)."""
    if level == "O2":
        items = models if isinstance(models, (list, tuple)) else [models]
        for m in items:
            m._cast_all(dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (needed for fp16; harmless for bf16).

    Reference parity: `python/paddle/amp/grad_scaler.py` (scale, minimize,
    found_inf handling, dynamic window growth) [UNVERIFIED].
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        from ..core.tensor import to_tensor
        return to_tensor(self._scale)

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops.math import multiply
        from ..core.tensor import to_tensor
        return multiply(var, to_tensor(np.asarray(
            self._scale, np.float32)))

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._params_with_grad():
            g = p.grad._value.astype(jnp.float32) * inv
            p.grad._local_value_update(g.astype(p.grad._value.dtype))
        # found_inf check (host sync; same cost profile as reference
        # check_finite_and_unscale kernel + D2H flag read) — routed
        # through the shared nonfinite sentinel so the skip-step is
        # attributed to a NAMED tensor (debugging.last_nonfinite())
        for i, p in enumerate(optimizer._params_with_grad()):
            if not bool(jnp.isfinite(p.grad._value.astype(
                    jnp.float32)).all()):
                from .debugging import first_nonfinite
                first_nonfinite([(p.name or f"param_{i}", p.grad)])
                found = True
                break
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    set_state_dict = load_state_dict


from . import debugging  # noqa: E402,F401
