"""paddle.autograd parity: backward, grad, PyLayer, hooks.

Reference parity: `python/paddle/autograd/` [UNVERIFIED — empty reference
mount].
"""
from __future__ import annotations

import threading as _threading

from ..core.autograd import (backward, grad, no_grad, enable_grad,
                             set_grad_enabled, is_grad_enabled)

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext", "saved_tensors_hooks"]


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.attrs = {}

    def save_for_backward(self, *tensors):
        hooks = saved_tensors_hooks._current()
        if hooks is not None:
            pack, _ = hooks
            self._saved = [pack(t) for t in tensors]
            self._saved_hooks = hooks
        else:
            self._saved = list(tensors)
            self._saved_hooks = None

    def _unpacked(self):
        if getattr(self, "_saved_hooks", None) is not None:
            _, unpack = self._saved_hooks
            return [unpack(p) for p in self._saved]
        return self._saved

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        pass

    def set_materialize_grads(self, value):
        pass


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op: subclass with static forward(ctx, ...) and
    backward(ctx, *grads)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as _ag
        from ..core.tensor import Tensor

        ctx = PyLayerContext()
        with _ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        outs = (outputs,) if single else tuple(outputs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs = [not t.stop_gradient for t in tensor_inputs]
        if _ag.is_grad_enabled() and any(needs):
            def vjp_fn(cots):
                if not isinstance(cots, tuple):
                    cots = (cots,)
                cot_tensors = tuple(
                    Tensor(c, _internal=True, stop_gradient=True)
                    for c in cots)
                with _ag.no_grad():
                    gin = cls.backward(ctx, *cot_tensors)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                vals = []
                gi = iter(gin)
                for t in tensor_inputs:
                    g = next(gi, None)
                    vals.append(None if g is None else g._value)
                return tuple(vals)

            node = _ag.GradNode(
                cls.__name__, vjp_fn, tensor_inputs, needs, len(outs),
                [(o._value.shape, o._value.dtype) for o in outs])
            wrapped = []
            for i, o in enumerate(outs):
                t = Tensor(o._value, _internal=True, stop_gradient=False)
                t._grad_node = node
                t._out_index = i
                wrapped.append(t)
            outs = tuple(wrapped)
        return outs[0] if single else outs


class saved_tensors_hooks:
    """Intercept PyLayer activation saving (reference:
    `paddle.autograd.saved_tensors_hooks` [UNVERIFIED]): while active,
    ``ctx.save_for_backward`` stores ``pack_hook(t)`` and backward
    reads ``unpack_hook(packed)`` — the offload-to-host / compress
    pattern.  Scope: PyLayer saves.  The built-in op backwards hold
    residuals inside jax.vjp closures, which XLA buffer-manages on
    device; rematerialization there is ``paddle.distributed.fleet.
    recompute`` / ``jax.checkpoint``, not host hooks.
    """

    _tls = _threading.local()

    @classmethod
    def _current(cls):
        return getattr(cls._tls, "active", None)

    def __init__(self, pack_hook, unpack_hook):
        self._hooks = (pack_hook, unpack_hook)

    def __enter__(self):
        self._prev = saved_tensors_hooks._current()
        saved_tensors_hooks._tls.active = self._hooks
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._tls.active = self._prev
        return False
