"""paddle.quantization: QAT / PTQ over fake-quant ops.

Reference parity: `python/paddle/quantization/` (QuantConfig, QAT, PTQ,
quanters/observers; static `paddle/static/quantization` passes
[UNVERIFIED — empty reference mount]).

TPU-native: the "quant program pass" is unnecessary — fake-quant is a
dispatched op (quantize→dequantize with a straight-through-estimator
custom gradient) inserted by wrapping layers, and XLA folds it into the
surrounding computation in both engines.  INT8 *execution* is not the
TPU deployment path (the MXU's low-precision format is bf16/int8 via
XLA's native quantized dots when available); the artifact of PTQ/QAT
here is the scale metadata + a quantize-aware float graph, which is the
same contract the reference's ONNX/Lite exporters consume.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..analysis.diagnostics import Diagnostic, DiagnosticReport
from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMax",
           "AbsmaxObserver", "quant_dequant", "quantize_weight_int8",
           "convert_to_int8", "logits_cosine", "greedy_match_ratio"]


@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), None


def _fq_bwd(res, g):
    # straight-through estimator: d(fake_quant)/dx ≈ 1
    return g, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant_dequant(x, scale, bits=8):
    """Quantize→dequantize with STE gradient (the fake_quantize op)."""
    qmax = float(2 ** (bits - 1) - 1)

    def impl(v, s, qmax):
        return _fake_quant(v.astype(jnp.float32), s, qmax).astype(v.dtype)

    return dispatch("fake_quantize_dequantize", impl, (x, scale),
                    dict(qmax=qmax))


class AbsmaxObserver:
    """Tracks running abs-max of a tensor (PTQ calibration).

    ``axis=None`` keeps one scalar over the whole tensor; an integer
    axis keeps one abs-max per slice along that axis (per-channel), the
    granularity the int8 weight path consumes."""

    def __init__(self, quant_bits=8, axis=None):
        self.bits = quant_bits
        self.axis = axis
        self._absmax = 0.0 if axis is None else None

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.axis is None:
            self._absmax = max(self._absmax,
                               float(jnp.max(jnp.abs(v))))
            return
        ax = self.axis % v.ndim
        red = tuple(i for i in range(v.ndim) if i != ax)
        cur = np.asarray(jnp.max(jnp.abs(v), axis=red), np.float32)
        self._absmax = cur if self._absmax is None \
            else np.maximum(self._absmax, cur)

    def scale(self):
        if self.axis is None:
            return max(self._absmax, 1e-8)
        if self._absmax is None:
            raise ValueError("per-channel observer never observed data")
        return np.maximum(self._absmax, 1e-8)


# ---------------------------------------------------------------------
# int8 weight-only execution (TPU serving path)
# ---------------------------------------------------------------------
Q_INT8_MAX = 127.0


def quantize_weight_int8(w, axis=-1, report=None):
    """Symmetric per-channel int8 weight quantization.

    Returns ``(w_q, scale)`` Tensors: int8 codes and the float32
    per-channel scale along ``axis`` such that ``w ≈ w_q * scale``
    (scale broadcast over the other dims).  Per-output-channel scale
    commutes with the contraction, so the matmul epilogue can apply it
    once on the f32 accumulator.  Channels whose abs-max is zero or
    nonfinite get scale 1.0 and a TPU404 diagnostic on ``report``.
    """
    v = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    v = v.astype(jnp.float32)
    ax = axis % v.ndim
    red = tuple(i for i in range(v.ndim) if i != ax)
    amax = np.asarray(jnp.max(jnp.abs(v), axis=red), np.float32)
    bad = ~np.isfinite(amax) | (amax <= 0.0)
    if bad.any() and report is not None:
        report.add(Diagnostic(
            "TPU404",
            f"{int(bad.sum())} of {amax.size} channels along axis "
            f"{ax} have zero or nonfinite abs-max; their scale is "
            "clamped to 1.0 and the channel dequantizes to zeros",
            site=f"quantize_weight_int8[shape={tuple(v.shape)}]",
            hint="check the calibration data / weight init for dead "
                 "or overflowed channels",
            data={"bad_channels": np.nonzero(bad)[0][:16].tolist()}))
    scale = np.where(bad, 1.0, amax / Q_INT8_MAX).astype(np.float32)
    bshape = [1] * v.ndim
    bshape[ax] = -1
    q = jnp.clip(jnp.round(v / jnp.asarray(scale).reshape(bshape)),
                 -Q_INT8_MAX, Q_INT8_MAX).astype(jnp.int8)
    return (Tensor(q, _internal=True, stop_gradient=True),
            Tensor(jnp.asarray(scale), _internal=True,
                   stop_gradient=True))


def convert_to_int8(model, report=None):
    """Convert every ``nn.Linear`` under ``model`` to int8 weight-only
    execution.

    The float ``weight`` parameter is dropped and replaced by two
    persistable buffers — ``weight_q`` (int8 codes) and
    ``weight_scale`` (float32 per-output-channel) — which round-trip
    through ``state_dict`` like any checkpointed tensor.  The forward
    pass then dispatches to the dequant-fused matmul epilogue
    (``F.linear_act_int8``).  Returns a ``DiagnosticReport`` carrying
    TPU404 findings for degenerate channels.
    """
    from .. import nn
    if report is None:
        report = DiagnosticReport(label="convert_to_int8")
    for layer in model.sublayers(include_self=True):
        if not isinstance(layer, nn.Linear):
            continue
        if "weight" not in layer._parameters:
            continue  # already converted (or weightless)
        w = layer._parameters["weight"]
        w_q, scale = quantize_weight_int8(w, axis=1, report=report)
        layer.weight = None
        layer.register_buffer("weight_q", w_q, persistable=True)
        layer.register_buffer("weight_scale", scale, persistable=True)
    return report


def logits_cosine(a, b):
    """Cosine similarity between two logits tensors (flattened f32)."""
    av = jnp.ravel(a._value if isinstance(a, Tensor)
                   else jnp.asarray(a)).astype(jnp.float32)
    bv = jnp.ravel(b._value if isinstance(b, Tensor)
                   else jnp.asarray(b)).astype(jnp.float32)
    denom = jnp.linalg.norm(av) * jnp.linalg.norm(bv) + 1e-12
    return float(jnp.vdot(av, bv) / denom)


def greedy_match_ratio(ref, hyp):
    """Position-wise token agreement between two lists of greedy
    sequences; length mismatches count as mismatched positions."""
    match = total = 0
    for a, b in zip(ref, hyp):
        total += max(len(a), len(b))
        match += sum(1 for x, y in zip(a, b) if x == y)
    return match / max(total, 1)


class FakeQuanterWithAbsMax:
    """QAT quanter: per-call abs-max scale + STE fake quant."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.bits = quant_bits
        self.moving_rate = moving_rate
        self._scale = None

    def __call__(self, x):
        cur = jnp.max(jnp.abs(
            x._value if isinstance(x, Tensor) else jnp.asarray(x)))
        try:
            # concrete (eager): update the EMA, held as a python float
            # so a jit re-trace can never leak a tracer into state
            curf = float(cur)
            if self._scale is None:
                self._scale = curf
            else:  # EMA of scales (reference moving-average absmax)
                self._scale = (self.moving_rate * self._scale
                               + (1 - self.moving_rate) * curf)
            scale = max(float(self._scale), 1e-8)
            # as a Tensor ARGUMENT, not a python static: the per-step
            # EMA value changes every call and a float would key a
            # fresh jit compile each step in the eager op cache
            scale = Tensor(jnp.asarray(scale, jnp.float32),
                           _internal=True, stop_gradient=True)
        except (jax.errors.ConcretizationTypeError, TypeError):
            # ConcretizationTypeError is what float(tracer) raises (it
            # is the PARENT of TracerArrayConversionError)
            # traced (to_static): use the frozen calibrated scale, or
            # the live per-batch max when never calibrated
            if self._scale is not None:
                scale = Tensor(jnp.asarray(max(float(self._scale), 1e-8),
                                           jnp.float32),
                               _internal=True, stop_gradient=True)
            else:
                scale = Tensor(
                    jnp.maximum(jax.lax.stop_gradient(cur), 1e-8),
                    _internal=True, stop_gradient=True)
        return quant_dequant(x, scale, self.bits)


class _FixedQuanter:
    """Frozen PTQ scale: reads its registered buffer on every call, so
    an in-place ``set_state_dict`` load retargets the quant scale."""

    def __init__(self, buf, bits=8):
        self._buf = buf
        self.bits = bits

    @property
    def _scale(self):
        return self._buf._value

    def __call__(self, x):
        scale = Tensor(jnp.maximum(self._buf._value, 1e-8),
                       _internal=True, stop_gradient=True)
        return quant_dequant(x, scale, self.bits)


class QuantConfig:
    """Which quanter to use for activations/weights, per layer type."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = (activation, weight)

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


def _make(quanter):
    if quanter is None:
        return None
    if callable(quanter) and not isinstance(
            quanter, (FakeQuanterWithAbsMax, AbsmaxObserver)):
        return quanter()  # a factory/class
    return quanter


class _QuantedWrapper(Layer):
    """Wraps a leaf layer: fake-quant its input and weight."""

    def __init__(self, inner, act_q, weight_q):
        super().__init__()
        self.inner = inner
        self._act_q = act_q
        self._weight_q = weight_q

    def forward(self, x, *args, **kwargs):
        if self._act_q is not None:
            x = self._act_q(x)
        w = getattr(self.inner, "weight", None)
        if self._weight_q is not None and w is not None:
            saved = w._value
            try:
                w._value = self._weight_q(
                    Tensor(saved, _internal=True))._value
                return self.inner(x, *args, **kwargs)
            finally:
                w._value = saved
        return self.inner(x, *args, **kwargs)


_DEFAULT_QUANTABLE = None


def _quantable_types():
    global _DEFAULT_QUANTABLE
    if _DEFAULT_QUANTABLE is None:
        from .. import nn
        _DEFAULT_QUANTABLE = (nn.Linear, nn.Conv2D)
    return _DEFAULT_QUANTABLE


def _wrap_model(model, config, act_factory):
    for name, child in list(getattr(model, "_sub_layers", {}).items()):
        if isinstance(child, _QuantedWrapper):
            continue
        if isinstance(child, _quantable_types()):
            act_q, w_q = config.config_for(child)
            wrapper = _QuantedWrapper(child, _make(act_q or act_factory),
                                      _make(w_q or act_factory))
            model._sub_layers[name] = wrapper
            setattr(model, name, wrapper)
        else:
            _wrap_model(child, config, act_factory)
    return model


class QAT:
    """Quantization-aware training: insert STE fake-quant wrappers."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return _wrap_model(model, self.config,
                           FakeQuanterWithAbsMax)

    def convert(self, model, inplace=True):
        """Strip the wrappers, leaving scale metadata on the layers."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for name, child in list(getattr(model, "_sub_layers",
                                        {}).items()):
            if isinstance(child, _QuantedWrapper):
                inner = child.inner
                scale = getattr(child._weight_q, "_scale", None)
                if scale is not None:
                    inner.weight_scale = float(np.asarray(scale))
                model._sub_layers[name] = inner
                setattr(model, name, inner)
            else:
                self.convert(child)
        return model


class PTQ:
    """Post-training quantization: observe activations on calibration
    batches, then bake scales."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()
        self._observers = []

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        ptq = self

        class _Observing(FakeQuanterWithAbsMax):
            def __init__(self):
                super().__init__()
                self.observer = AbsmaxObserver()
                ptq._observers.append(self.observer)

            def __call__(self, x):
                self.observer.observe(x)
                return x  # observation only during calibration

        return _wrap_model(model, self.config, _Observing)

    def convert(self, model, inplace=True):
        """After calibration: replace observers with fixed-scale
        fake-quant (so the exported graph carries the PTQ scales).

        Each frozen scale is registered as a persistable buffer on the
        wrapper (``act_scale`` / ``w_scale``), so it lands in
        ``state_dict`` and a later ``set_state_dict`` retargets the
        quanter in place — calibration round-trips through
        checkpoints."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for name, child in list(getattr(model, "_sub_layers",
                                        {}).items()):
            if isinstance(child, _QuantedWrapper):
                for attr, bname in (("_act_q", "act_scale"),
                                    ("_weight_q", "w_scale")):
                    q = getattr(child, attr)
                    obs = getattr(q, "observer", None)
                    if obs is not None:
                        buf = Tensor(
                            jnp.asarray(obs.scale(), jnp.float32),
                            _internal=True, stop_gradient=True)
                        child.register_buffer(bname, buf,
                                              persistable=True)
                        setattr(child, attr,
                                _FixedQuanter(buf, obs.bits))
            else:
                self.convert(child)
        return model
