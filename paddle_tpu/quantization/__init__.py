"""paddle.quantization: QAT / PTQ over fake-quant ops.

Reference parity: `python/paddle/quantization/` (QuantConfig, QAT, PTQ,
quanters/observers; static `paddle/static/quantization` passes
[UNVERIFIED — empty reference mount]).

TPU-native: the "quant program pass" is unnecessary — fake-quant is a
dispatched op (quantize→dequantize with a straight-through-estimator
custom gradient) inserted by wrapping layers, and XLA folds it into the
surrounding computation in both engines.  INT8 *execution* is not the
TPU deployment path (the MXU's low-precision format is bf16/int8 via
XLA's native quantized dots when available); the artifact of PTQ/QAT
here is the scale metadata + a quantize-aware float graph, which is the
same contract the reference's ONNX/Lite exporters consume.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMax",
           "AbsmaxObserver", "quant_dequant"]


@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), None


def _fq_bwd(res, g):
    # straight-through estimator: d(fake_quant)/dx ≈ 1
    return g, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant_dequant(x, scale, bits=8):
    """Quantize→dequantize with STE gradient (the fake_quantize op)."""
    qmax = float(2 ** (bits - 1) - 1)

    def impl(v, s, qmax):
        return _fake_quant(v.astype(jnp.float32), s, qmax).astype(v.dtype)

    return dispatch("fake_quantize_dequantize", impl, (x, scale),
                    dict(qmax=qmax))


class AbsmaxObserver:
    """Tracks running abs-max of a tensor (PTQ calibration)."""

    def __init__(self, quant_bits=8):
        self.bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        v = float(jnp.max(jnp.abs(
            x._value if isinstance(x, Tensor) else jnp.asarray(x))))
        self._absmax = max(self._absmax, v)

    def scale(self):
        return max(self._absmax, 1e-8)


class FakeQuanterWithAbsMax:
    """QAT quanter: per-call abs-max scale + STE fake quant."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.bits = quant_bits
        self.moving_rate = moving_rate
        self._scale = None

    def __call__(self, x):
        cur = jnp.max(jnp.abs(
            x._value if isinstance(x, Tensor) else jnp.asarray(x)))
        try:
            # concrete (eager): update the EMA, held as a python float
            # so a jit re-trace can never leak a tracer into state
            curf = float(cur)
            if self._scale is None:
                self._scale = curf
            else:  # EMA of scales (reference moving-average absmax)
                self._scale = (self.moving_rate * self._scale
                               + (1 - self.moving_rate) * curf)
            scale = max(float(self._scale), 1e-8)
            # as a Tensor ARGUMENT, not a python static: the per-step
            # EMA value changes every call and a float would key a
            # fresh jit compile each step in the eager op cache
            scale = Tensor(jnp.asarray(scale, jnp.float32),
                           _internal=True, stop_gradient=True)
        except (jax.errors.ConcretizationTypeError, TypeError):
            # ConcretizationTypeError is what float(tracer) raises (it
            # is the PARENT of TracerArrayConversionError)
            # traced (to_static): use the frozen calibrated scale, or
            # the live per-batch max when never calibrated
            if self._scale is not None:
                scale = Tensor(jnp.asarray(max(float(self._scale), 1e-8),
                                           jnp.float32),
                               _internal=True, stop_gradient=True)
            else:
                scale = Tensor(
                    jnp.maximum(jax.lax.stop_gradient(cur), 1e-8),
                    _internal=True, stop_gradient=True)
        return quant_dequant(x, scale, self.bits)


class QuantConfig:
    """Which quanter to use for activations/weights, per layer type."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = (activation, weight)

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


def _make(quanter):
    if quanter is None:
        return None
    if callable(quanter) and not isinstance(
            quanter, (FakeQuanterWithAbsMax, AbsmaxObserver)):
        return quanter()  # a factory/class
    return quanter


class _QuantedWrapper(Layer):
    """Wraps a leaf layer: fake-quant its input and weight."""

    def __init__(self, inner, act_q, weight_q):
        super().__init__()
        self.inner = inner
        self._act_q = act_q
        self._weight_q = weight_q

    def forward(self, x, *args, **kwargs):
        if self._act_q is not None:
            x = self._act_q(x)
        w = getattr(self.inner, "weight", None)
        if self._weight_q is not None and w is not None:
            saved = w._value
            try:
                w._value = self._weight_q(
                    Tensor(saved, _internal=True))._value
                return self.inner(x, *args, **kwargs)
            finally:
                w._value = saved
        return self.inner(x, *args, **kwargs)


_DEFAULT_QUANTABLE = None


def _quantable_types():
    global _DEFAULT_QUANTABLE
    if _DEFAULT_QUANTABLE is None:
        from .. import nn
        _DEFAULT_QUANTABLE = (nn.Linear, nn.Conv2D)
    return _DEFAULT_QUANTABLE


def _wrap_model(model, config, act_factory):
    for name, child in list(getattr(model, "_sub_layers", {}).items()):
        if isinstance(child, _QuantedWrapper):
            continue
        if isinstance(child, _quantable_types()):
            act_q, w_q = config.config_for(child)
            wrapper = _QuantedWrapper(child, _make(act_q or act_factory),
                                      _make(w_q or act_factory))
            model._sub_layers[name] = wrapper
            setattr(model, name, wrapper)
        else:
            _wrap_model(child, config, act_factory)
    return model


class QAT:
    """Quantization-aware training: insert STE fake-quant wrappers."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return _wrap_model(model, self.config,
                           FakeQuanterWithAbsMax)

    def convert(self, model, inplace=True):
        """Strip the wrappers, leaving scale metadata on the layers."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for name, child in list(getattr(model, "_sub_layers",
                                        {}).items()):
            if isinstance(child, _QuantedWrapper):
                inner = child.inner
                scale = getattr(child._weight_q, "_scale", None)
                if scale is not None:
                    inner.weight_scale = float(np.asarray(scale))
                model._sub_layers[name] = inner
                setattr(model, name, inner)
            else:
                self.convert(child)
        return model


class PTQ:
    """Post-training quantization: observe activations on calibration
    batches, then bake scales."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()
        self._observers = []

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        ptq = self

        class _Observing(FakeQuanterWithAbsMax):
            def __init__(self):
                super().__init__()
                self.observer = AbsmaxObserver()
                ptq._observers.append(self.observer)

            def __call__(self, x):
                self.observer.observe(x)
                return x  # observation only during calibration

        return _wrap_model(model, self.config, _Observing)

    def convert(self, model, inplace=True):
        """After calibration: replace observers with fixed-scale
        fake-quant (so the exported graph carries the PTQ scales)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for name, child in list(getattr(model, "_sub_layers",
                                        {}).items()):
            if isinstance(child, _QuantedWrapper):
                for attr in ("_act_q", "_weight_q"):
                    q = getattr(child, attr)
                    obs = getattr(q, "observer", None)
                    if obs is not None:
                        scale = obs.scale()
                        fixed = FakeQuanterWithAbsMax()
                        fixed._scale = jnp.asarray(scale, jnp.float32)
                        fixed.moving_rate = 1.0  # frozen
                        setattr(child, attr, fixed)
            else:
                self.convert(child)
        return model
