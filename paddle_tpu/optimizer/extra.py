"""Additional optimizers: Rprop, ASGD, NAdam, RAdam, LBFGS.

Reference parity: `python/paddle/optimizer/{rprop,asgd,nadam,radam,
lbfgs}.py` [UNVERIFIED — empty reference mount].  Each implements the
framework Optimizer contract: `_pure_update` (one fused traced update —
used by the static Executor/DistModel and by the eager path below) and
`_static_state`.  LBFGS is closure-driven and eager-only, like the
reference (its inner line search re-evaluates the loss).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["Rprop", "ASGD", "NAdam", "RAdam", "LBFGS"]


class _PureApplied(Optimizer):
    """Eager `_apply` driven by `_pure_update` (one implementation of
    the math).  The update closes over python state, so it takes the
    plain eager path rather than the per-op jit cache — fine for these
    optimizers; the compiled engines fuse `_pure_update` directly."""

    def _apply(self, params):
        state = self._static_state(params)
        lr = self._lr_tensor._value
        step = self._step_count._value
        pvals = tuple(p._value for p in params)
        gvals = tuple(p.grad._value for p in params)
        ovals = tuple(t._value for t in state)
        new_p, new_o = self._pure_update(lr, step, pvals, gvals, ovals,
                                         params)
        for p, v in zip(params, new_p):
            p._inplace_update(v)
        for t, v in zip(state, new_o):
            t._inplace_update(v)


class Rprop(_PureApplied):
    """Resilient backprop: sign-based per-element step sizes."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_range = (float(learning_rate_range[0]),
                          float(learning_rate_range[1]))
        self._etas = (float(etas[0]), float(etas[1]))

    def _static_state(self, params):
        out = []
        for p in params:
            out.append(self._acc("prev_grad", p))
            out.append(self._acc("step_size", p,
                                 init=float(self._current_lr())))
        return out

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        lo, hi = self._lr_range
        eta_m, eta_p = self._etas
        new_p, new_o = [], []
        for i, (p, g) in enumerate(zip(param_vals, grads)):
            prev = opt_vals[2 * i]
            size = opt_vals[2 * i + 1]
            gf = g.astype(jnp.float32)
            sign = jnp.sign(gf * prev)
            size2 = jnp.clip(
                jnp.where(sign > 0, size * eta_p,
                          jnp.where(sign < 0, size * eta_m, size)),
                lo, hi)
            # on sign change the step is skipped and the grad zeroed
            g_eff = jnp.where(sign < 0, 0.0, gf)
            new_p.append((p.astype(jnp.float32)
                          - size2 * jnp.sign(g_eff)).astype(p.dtype))
            new_o.extend([g_eff, size2])
        return tuple(new_p), tuple(new_o)


class ASGD(_PureApplied):
    """Averaged SGD: steps on the running mean of the last ~batch_num
    gradients (the reference's gradient-averaging window, kept as a
    streaming mean `d`), plus a running average of the iterates in `ax`
    (swap in for evaluation via state_dict, the reference contract)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._batch_num = max(1, int(batch_num))

    def _static_state(self, params):
        out = []
        for p in params:
            out.append(self._acc("grad_avg", p))
            out.append(self._acc("ax", p))
        return out

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        wd = self._decay_coeff()
        t = step.astype(jnp.float32) + 1.0
        win = jnp.minimum(t, float(self._batch_num))
        new_p, new_o = [], []
        for i, (p, g) in enumerate(zip(param_vals, grads)):
            d = opt_vals[2 * i]
            ax = opt_vals[2 * i + 1]
            gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
            d2 = d + (gf - d) / win          # windowed gradient mean
            p2 = p.astype(jnp.float32) - lr * d2
            new_p.append(p2.astype(p.dtype))
            new_o.extend([d2, ax + (p2 - ax) / t])
        return tuple(new_p), tuple(new_o)


class NAdam(_PureApplied):
    """Adam with Nesterov momentum (Dozat 2016)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = float(beta1), float(beta2)
        self._eps = float(epsilon)
        self._psi = float(momentum_decay)

    def _static_state(self, params):
        out = []
        for p in params:
            out.append(self._acc("moment1", p))
            out.append(self._acc("moment2", p))
        # the cumulative momentum product is real STATE (Dozat's
        # schedule); owned by the OPTIMIZER, not keyed to any param —
        # a changing first-param (frozen layers) must not reset it
        if not hasattr(self, "_mu_product_t"):
            self._mu_product_t = Tensor(jnp.asarray(1.0, jnp.float32),
                                        _internal=True,
                                        stop_gradient=True)
            self._mu_product_t.name = "nadam_mu_product"
            self._mu_product_t.persistable = True
        out.append(self._mu_product_t)
        return out

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        wd = self._decay_coeff()
        b1, b2, eps, psi = self._b1, self._b2, self._eps, self._psi
        t = step.astype(jnp.float32) + 1.0
        mu_t = b1 * (1.0 - 0.5 * 0.96 ** (t * psi))
        mu_t1 = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1.0) * psi))
        mprod_t = opt_vals[-1] * mu_t
        mprod_t1 = mprod_t * mu_t1
        new_p, new_o = [], []
        for i, (p, g) in enumerate(zip(param_vals, grads)):
            m = opt_vals[2 * i]
            v = opt_vals[2 * i + 1]
            gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
            m2 = b1 * m + (1 - b1) * gf
            v2 = b2 * v + (1 - b2) * gf * gf
            m_hat = (mu_t1 * m2 / (1 - mprod_t1)
                     + (1 - mu_t) * gf / (1 - mprod_t))
            v_hat = v2 / (1 - b2 ** t)
            new_p.append((p.astype(jnp.float32)
                          - lr * m_hat / (jnp.sqrt(v_hat) + eps)
                          ).astype(p.dtype))
            new_o.extend([m2, v2])
        new_o.append(mprod_t)
        return tuple(new_p), tuple(new_o)


class RAdam(_PureApplied):
    """Rectified Adam (Liu et al. 2019): variance-rectified warmup."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = float(beta1), float(beta2)
        self._eps = float(epsilon)

    def _static_state(self, params):
        out = []
        for p in params:
            out.append(self._acc("moment1", p))
            out.append(self._acc("moment2", p))
        return out

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        wd = self._decay_coeff()
        b1, b2, eps = self._b1, self._b2, self._eps
        t = step.astype(jnp.float32) + 1.0
        rho_inf = 2.0 / (1.0 - b2) - 1.0
        b2t = b2 ** t
        rho_t = rho_inf - 2.0 * t * b2t / (1.0 - b2t)
        rect = jnp.sqrt(
            ((rho_t - 4) * (rho_t - 2) * rho_inf)
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8))
        use_rect = rho_t > 5.0
        new_p, new_o = [], []
        for i, (p, g) in enumerate(zip(param_vals, grads)):
            m = opt_vals[2 * i]
            v = opt_vals[2 * i + 1]
            gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
            m2 = b1 * m + (1 - b1) * gf
            v2 = b2 * v + (1 - b2) * gf * gf
            m_hat = m2 / (1 - b1 ** t)
            v_hat = jnp.sqrt(v2 / (1.0 - b2t))
            upd = jnp.where(use_rect,
                            rect * m_hat / (v_hat + eps),
                            m_hat)
            new_p.append((p.astype(jnp.float32) - lr * upd
                          ).astype(p.dtype))
            new_o.extend([m2, v2])
        return tuple(new_p), tuple(new_o)


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-driven steps (eager only).

    step(closure) re-evaluates the loss as the reference does; the
    two-loop recursion runs on device arrays, history on the host."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self.max_iter = int(max_iter)
        self.max_eval = (int(max_eval) if max_eval is not None
                         else self.max_iter * 5 // 4)
        self.tol_grad = float(tolerance_grad)
        self.tol_change = float(tolerance_change)
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"line_search_fn must be None or 'strong_wolfe', got "
                f"{line_search_fn!r}")
        self.line_search_fn = line_search_fn
        self._s, self._y = [], []

    def _flat(self, params, attr):
        wd = self._decay_coeff()
        vs = []
        for p in params:
            if attr == "p":
                v = p._value.astype(jnp.float32)
            else:
                v = p.grad._value.astype(jnp.float32)
                if wd:
                    v = v + wd * p._value.astype(jnp.float32)
            vs.append(v.reshape(-1))
        return jnp.concatenate(vs)

    def _unflatten_to(self, params, flat):
        off = 0
        for p in params:
            n = int(np.prod(p._value.shape))
            p._inplace_update(
                flat[off:off + n].reshape(p._value.shape).astype(
                    p._value.dtype))
            off += n

    @autograd.no_grad()
    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that "
                             "re-evaluates the loss")
        n_evals = [0]

        def eval_closure():
            with autograd.enable_grad():
                loss = closure()
            n_evals[0] += 1
            return loss

        loss = eval_closure()
        # only parameters the closure actually gradded participate
        # (frozen/unused submodules must not crash the flatten)
        params = self._params_with_grad()
        if not params:
            return loss
        if self._grad_clip is not None:
            self._grad_clip(params)
        for _ in range(self.max_iter):
            g = self._flat(params, "g")
            if float(jnp.max(jnp.abs(g))) <= self.tol_grad:
                break
            # two-loop recursion over (s, y) history
            q = g
            alphas = []
            for s, y in reversed(list(zip(self._s, self._y))):
                rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
                a = rho * jnp.vdot(s, q)
                alphas.append((a, rho, s, y))
                q = q - a * y
            if self._y:
                y_last, s_last = self._y[-1], self._s[-1]
                gamma = jnp.vdot(s_last, y_last) / jnp.maximum(
                    jnp.vdot(y_last, y_last), 1e-10)
                q = q * gamma
            for a, rho, s, y in reversed(alphas):
                b = rho * jnp.vdot(y, q)
                q = q + (a - b) * s
            d = -q
            x0 = self._flat(params, "p")
            lr = float(self._current_lr())
            f0 = float(loss)
            t = lr
            gtd = float(jnp.vdot(g, d))
            if self.line_search_fn is None:
                # reference contract: no line search → one fixed-lr step
                self._unflatten_to(params, x0 + t * d)
                self.clear_grad()
                loss = eval_closure()
            else:  # 'strong_wolfe' ~ backtracking sufficient decrease
                for _ls in range(10):
                    self._unflatten_to(params, x0 + t * d)
                    self.clear_grad()
                    loss = eval_closure()
                    if float(loss) <= f0 + 1e-4 * t * gtd:
                        break
                    t *= 0.5
            g_new = self._flat(params, "g")
            s_vec = t * d
            y_vec = g_new - g
            if float(jnp.vdot(s_vec, y_vec)) > 1e-10:
                self._s.append(s_vec)
                self._y.append(y_vec)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            if float(jnp.max(jnp.abs(s_vec))) <= self.tol_change:
                break
            if n_evals[0] >= self.max_eval:
                break
        self._step_count._inplace_update(
            np.asarray(self._step_count._value) + 1)
        return loss
