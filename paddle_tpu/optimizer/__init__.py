"""paddle.optimizer parity surface."""
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb)
from .extra import Rprop, ASGD, NAdam, RAdam, LBFGS
from . import lr
