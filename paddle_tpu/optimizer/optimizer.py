"""Optimizers (paddle.optimizer parity) with fused multi-tensor updates.

Reference parity: `python/paddle/optimizer/optimizer.py`, `adamw.py` → phi
`gpu/adamw_kernel.cu` multi-tensor path [UNVERIFIED — empty reference
mount].

TPU-native: ``step()`` performs ONE dispatch over all parameters (flat
lists in, flat lists out) so the whole optimizer compiles to a single fused
XLA program — the multi_tensor_adam equivalent, and under
``paddle.jit.to_static`` the update fuses into the train-step executable.
The learning rate rides in a Tensor so schedulers don't retrigger
compilation.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..core import autograd
from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb",
           "register_pre_step_hook", "run_pre_step_hooks"]

# Pre-step hooks: callables(optimizer, params) run at the top of every
# step() — the fault-tolerance layer's seam (gradient poisoning under a
# FaultPlan, NaN sentinels) without the optimizer importing any of it.
_pre_step_hooks = []
_hooks_ran = threading.local()


def register_pre_step_hook(fn):
    """Register ``fn(optimizer, params)`` to run before each update.
    Returns a zero-arg remover."""
    _pre_step_hooks.append(fn)

    def remove():
        try:
            _pre_step_hooks.remove(fn)
        except ValueError:
            pass
    return remove


def run_pre_step_hooks(optimizer, params):
    """Run the hooks ahead of step() — sentinels (amp.debugging.
    skip_step_on_nonfinite) call this so injected faults land BEFORE
    their gradient check; the immediately-following step() won't run
    the hooks a second time."""
    for hook in _pre_step_hooks:
        hook(optimizer, params)
    _hooks_ran.flag = True


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._learning_rate = learning_rate
        self._lr_tensor = to_tensor(float(self._current_lr()),
                                    dtype="float32")
        self._lr_tensor.name = "learning_rate"
        self._lr_tensor.persistable = True
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._param_groups = None
        if parameters and isinstance(parameters[0], dict):
            self._param_groups = parameters
            flat = []
            for g in parameters:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators = OrderedDict()  # name -> {param_name: Tensor}
        self._step_count = to_tensor(0, dtype="int64")
        self._step_count.persistable = True
        self._master_weights = {}

    # ---- lr handling ----
    def _current_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def get_lr(self):
        return self._current_lr()

    def set_lr(self, value):
        self._learning_rate = float(value)
        self._lr_tensor._inplace_update(
            jnp.asarray(value, jnp.float32))

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    def _sync_lr(self):
        lr = self._current_lr()
        # skip the per-step h2d transfer (and, under lazy mode, a
        # spurious leaf-signature change) while the lr is unchanged —
        # the common case for constant-lr training
        if lr == getattr(self, "_lr_last", None):
            return
        self._lr_last = lr
        self._lr_tensor._inplace_update(
            jnp.asarray(lr, jnp.float32))

    # ---- accumulators ----
    def _acc(self, name, param, init=0.0, shape=None, dtype=None):
        d = self._accumulators.setdefault(name, {})
        if param.name not in d:
            v = jnp.full(shape if shape is not None else param._value.shape,
                         init,
                         dtype if dtype is not None else (
                             jnp.float32 if param._value.dtype in
                             (jnp.bfloat16, jnp.float16)
                             else param._value.dtype))
            t = Tensor(v, _internal=True)
            t.name = f"{param.name}_{name}"
            t.persistable = True
            d[param.name] = t
        return d[param.name]

    def _params_with_grad(self):
        out = []
        for p in (self._parameter_list or []):
            if p.grad is not None and not p.stop_gradient:
                out.append(p)
        return out

    # ---- main API ----
    @autograd.no_grad()
    def step(self):
        self._sync_lr()
        params = self._params_with_grad()
        if not params:
            return
        if getattr(_hooks_ran, "flag", False):
            _hooks_ran.flag = False  # sentinel already ran them
        else:
            for hook in _pre_step_hooks:
                hook(self, params)
        if getattr(self, "_skip_apply", False):
            # a hook (gradient accumulation, skip-step sentinel) asked
            # this step() to be a no-op: keep accumulated grads AND the
            # step counter untouched (Adam bias correction must count
            # applied updates only)
            self._skip_apply = False
            return
        if self._grad_clip is not None:
            self._grad_clip(params)
        l1 = self._l1_coeff()
        if l1:
            # L1Decay: g += coeff * sign(p), post-clip like the
            # reference's append_regularization_ops ordering (step()
            # already runs under no_grad)
            for p in params:
                p.grad = p.grad + l1 * p.detach().sign()
        self._apply(params)
        self._step_count._inplace_update(self._step_count._value + 1)

    def _apply(self, params):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        for p in (self._parameter_list or []):
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.framework import Variable, in_static_mode, \
            default_main_program

        # `parameters`/`no_grad_set` restrict the update set for THIS
        # call only (paddle semantics); the constructor list must not be
        # permanently overwritten by one minimize() invocation.
        scoped = self._parameter_list
        if parameters is not None:
            scoped = list(parameters)
        if no_grad_set:
            excl = {id(t) for t in no_grad_set}
            if scoped:
                scoped = [p for p in scoped if id(p) not in excl]
            else:
                # no explicit list ("all trainables"): record the
                # exclusion for the Executor's update-set selection —
                # an empty _parameter_list would read as "no
                # restriction" there and as "update nothing" in eager
                self._no_grad_ids = (
                    getattr(self, "_no_grad_ids", set()) | excl)
        if in_static_mode() and isinstance(loss, Variable):
            # static graph: attach to the program; Executor lowers
            # forward+grad+update into one XLA executable.
            prog = default_main_program()
            prog._optimize_info = (self, loss)
            prog._loss_var = loss
            if scoped is not self._parameter_list:
                prog._minimize_params = list(scoped)
            return None, None
        loss.backward()
        if scoped is not self._parameter_list:
            prev, self._parameter_list = self._parameter_list, scoped
            try:
                self.step()
            finally:
                self._parameter_list = prev
        else:
            self.step()
        return None, None

    # ---- static-graph path (used by static.Executor) ----
    def _ensure_static_state(self, params):
        """Materialize accumulators for `params`; returns the flat state
        Tensor list in the layout `_pure_update` expects."""
        self._sync_lr()
        return self._static_state(params)

    def _static_state(self, params):
        return []

    def _clip_static_grads(self, grads):
        """Apply this optimizer's grad_clip in traced code (shared by
        the direct static path and meta-optimizer wrappers)."""
        if self._grad_clip is None:
            return grads
        from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, \
            ClipGradByValue
        if isinstance(self._grad_clip, ClipGradByGlobalNorm):
            total = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in grads))
            cn = self._grad_clip.clip_norm
            scale = cn / jnp.maximum(total, cn)
            return tuple((g.astype(jnp.float32) * scale).astype(g.dtype)
                         for g in grads)
        if isinstance(self._grad_clip, ClipGradByNorm):
            cn = self._grad_clip.clip_norm
            out = []
            for g in grads:
                n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                s = cn / jnp.maximum(n, cn)
                out.append((g.astype(jnp.float32) * s).astype(g.dtype))
            return tuple(out)
        if isinstance(self._grad_clip, ClipGradByValue):
            return tuple(jnp.clip(g, self._grad_clip.min,
                                  self._grad_clip.max) for g in grads)
        return grads

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        # `lr` and `step` are traced per-step values when the caller
        # threads them as executable arguments (Executor/DistModel/the
        # pipeline engine do).  Baking them at trace time would freeze
        # an LRScheduler's changes AND Adam/AdamW's bias correction
        # (`1 - beta**step`) at the first step's values for the whole
        # cached-executable lifetime.
        if lr is None:
            lr = self._lr_tensor._value
        if step is None:
            step = self._step_count._value
            # advance the counter host-side (numpy): this runs while
            # TRACING the compiled step, and any jnp op here (even
            # asarray) would be lifted into the trace, leaking a tracer
            # into the eager step counter (it then poisons
            # optimizer.state_dict()).
            self._step_count._inplace_update(np.asarray(step) + 1)
        grads = self._clip_static_grads(grads)
        grads = self._l1_grads(grads, param_vals)
        return self._pure_update(lr, step, param_vals, grads, opt_vals,
                                 params)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        raise NotImplementedError(
            f"{type(self).__name__} does not support static-graph mode yet")

    # ---- state dict ----
    def state_dict(self):
        out = {}
        for acc_name, d in self._accumulators.items():
            for pname, t in d.items():
                out[f"{pname}_{acc_name}"] = t
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        out["global_step"] = self._step_count
        # auto-generated parameter names are session-counter dependent;
        # recording the save-time order lets set_state_dict map state
        # POSITIONALLY onto a freshly-built optimizer whose names differ
        out["__param_names__"] = [
            p.name for p in (self._parameter_list or [])
            if not p.stop_gradient]
        return out

    def set_state_dict(self, state_dict):
        def _val(src):
            return src._value if isinstance(src, Tensor) else \
                jnp.asarray(np.asarray(src))

        saved_names = state_dict.get("__param_names__")
        if saved_names is not None:
            # positional mapping: saved param i ↔ current param i; the
            # accumulator is MATERIALIZED via _acc so a fresh optimizer
            # (empty _accumulators) restores correctly
            cur = [p for p in (self._parameter_list or [])
                   if not p.stop_gradient]
            by_len = sorted(saved_names, key=len, reverse=True)
            pos = {n: i for i, n in enumerate(saved_names)}
            for key, src in state_dict.items():
                if key in ("LR_Scheduler", "global_step",
                           "__param_names__"):
                    continue
                for n in by_len:  # longest prefix wins (names nest)
                    if key.startswith(n + "_"):
                        i = pos[n]
                        if i < len(cur):
                            acc_name = key[len(n) + 1:]
                            t = self._acc(acc_name, cur[i])
                            t._inplace_update(jnp.asarray(
                                _val(src), t._value.dtype))
                        break
        else:  # legacy dicts: name-matched into existing accumulators
            for acc_name, d in self._accumulators.items():
                for pname in d:
                    key = f"{pname}_{acc_name}"
                    if key in state_dict:
                        d[pname]._inplace_update(jnp.asarray(
                            _val(state_dict[key]),
                            d[pname]._value.dtype))
        if "LR_Scheduler" in state_dict and isinstance(
                self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        if "global_step" in state_dict:
            src = state_dict["global_step"]
            v = src._value if isinstance(src, Tensor) else \
                jnp.asarray(src)
            self._step_count._inplace_update(v)

    set_dict = set_state_dict

    def _decay_coeff(self):
        """L2 coefficient for the per-optimizer `g + wd*p` decay term.
        L1Decay returns 0.0 here — its `coeff*sign(p)` term is added to
        the gradients at the two common points (step /_static_update),
        not per-optimizer (it used to silently apply as L2)."""
        wd = self._weight_decay
        if wd is None:
            return 0.0
        from ..regularizer import L1Decay
        if isinstance(wd, L1Decay):
            return 0.0
        if hasattr(wd, "_coeff"):
            return float(wd._coeff)
        return float(wd)

    def _l1_coeff(self):
        from ..regularizer import L1Decay
        wd = self._weight_decay
        return float(wd._coeff) if isinstance(wd, L1Decay) else 0.0

    def _l1_grads(self, grads, param_vals):
        """Traced L1Decay term: g += coeff*sign(p).  Shared by every
        traced update entry point that bypasses _static_update (the
        pipeline schedules call _pure_update directly)."""
        l1 = self._l1_coeff()
        if not l1:
            return grads
        return tuple(
            (g.astype(jnp.float32)
             + l1 * jnp.sign(pv.astype(jnp.float32))).astype(g.dtype)
            for g, pv in zip(grads, param_vals))


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        wd = self._decay_coeff()
        new_p = tuple(
            (p.astype(jnp.float32) - lr * (
                g.astype(jnp.float32) + wd * p.astype(jnp.float32))
             ).astype(p.dtype)
            for p, g in zip(param_vals, grads))
        return new_p, opt_vals

    def _apply(self, params):
        wd = self._decay_coeff()

        def impl(lr, *pg, wd, n):
            ps, gs = pg[:n], pg[n:]
            out = []
            for p, g in zip(ps, gs):
                g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                out.append((p.astype(jnp.float32) -
                            lr * g).astype(p.dtype))
            return tuple(out)

        grads = [p.grad for p in params]
        outs = dispatch("sgd", impl, (self._lr_tensor,) + tuple(params) +
                        tuple(grads), dict(wd=wd, n=len(params)),
                        differentiable=False)
        for p, new in zip(params, outs):
            p._inplace_update(new._value)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _static_state(self, params):
        return [self._acc("velocity", p) for p in params]

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        wd = self._decay_coeff()
        mu = float(self._momentum)
        new_p, new_v = [], []
        for p, g, v in zip(param_vals, grads, opt_vals):
            gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
            v2 = mu * v + gf
            upd = gf + mu * v2 if self._nesterov else v2
            new_p.append((p.astype(jnp.float32) - lr * upd).astype(p.dtype))
            new_v.append(v2)
        return tuple(new_p), tuple(new_v)

    def _apply(self, params):
        wd = self._decay_coeff()
        vels = [self._acc("velocity", p) for p in params]

        def impl(lr, *pgv, mu, wd, nesterov, n):
            ps, gs, vs = pgv[:n], pgv[n:2 * n], pgv[2 * n:]
            new_p, new_v = [], []
            for p, g, v in zip(ps, gs, vs):
                g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                v2 = mu * v + g
                if nesterov:
                    upd = g + mu * v2
                else:
                    upd = v2
                new_p.append((p.astype(jnp.float32) -
                              lr * upd).astype(p.dtype))
                new_v.append(v2)
            return tuple(new_p) + tuple(new_v)

        grads = [p.grad for p in params]
        outs = dispatch("momentum", impl,
                        (self._lr_tensor,) + tuple(params) + tuple(grads) +
                        tuple(vels),
                        dict(mu=float(self._momentum), wd=wd,
                             nesterov=self._nesterov, n=len(params)),
                        differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for v, new in zip(vels, outs[n:]):
            v._inplace_update(new._value)


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=True,
                 decoupled=False, apply_decay_param_fun=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._decoupled = decoupled
        self._apply_decay_param_fun = apply_decay_param_fun

    def _static_state(self, params):
        return ([self._acc("moment1", p) for p in params] +
                [self._acc("moment2", p) for p in params])

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        n = len(param_vals)
        ms, vs = opt_vals[:n], opt_vals[n:]
        wd = self._decay_coeff()
        b1 = self._beta1() if callable(self._beta1) else float(self._beta1)
        b2 = self._beta2() if callable(self._beta2) else float(self._beta2)
        eps = float(self._epsilon)
        tf = (step + 1).astype(jnp.float32)
        bc1 = 1.0 - jnp.power(b1, tf)
        bc2 = 1.0 - jnp.power(b2, tf)
        new_p, new_m, new_v = [], [], []
        for p, g, m_, v_ in zip(param_vals, grads, ms, vs):
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            if not self._decoupled and wd != 0.0:
                gf = gf + wd * pf
            m2 = b1 * m_ + (1 - b1) * gf
            v2 = b2 * v_ + (1 - b2) * gf * gf
            upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self._decoupled and wd != 0.0:
                upd = upd + wd * pf
            new_p.append((pf - lr * upd).astype(p.dtype))
            new_m.append(m2)
            new_v.append(v2)
        return tuple(new_p), tuple(new_m) + tuple(new_v)

    def _apply(self, params):
        wd = self._decay_coeff()
        m = [self._acc("moment1", p) for p in params]
        v = [self._acc("moment2", p) for p in params]
        decay_mask = tuple(
            1.0 if (self._apply_decay_param_fun is None or
                    self._apply_decay_param_fun(p.name)) and
            getattr(p, "no_weight_decay", False) is False else 0.0
            for p in params)
        b1 = self._beta1() if callable(self._beta1) else float(self._beta1)
        b2 = self._beta2() if callable(self._beta2) else float(self._beta2)

        def impl(lr, t, *pgmv, b1, b2, eps, wd, decoupled, n, mask):
            ps, gs = pgmv[:n], pgmv[n:2 * n]
            ms, vs = pgmv[2 * n:3 * n], pgmv[3 * n:]
            tf = (t + 1).astype(jnp.float32)
            bc1 = 1.0 - jnp.power(b1, tf)
            bc2 = 1.0 - jnp.power(b2, tf)
            new_p, new_m, new_v = [], [], []
            for p, g, m_, v_, dm in zip(ps, gs, ms, vs, mask):
                pf = p.astype(jnp.float32)
                gf = g.astype(jnp.float32)
                if not decoupled and wd != 0.0:
                    gf = gf + wd * dm * pf
                m2 = b1 * m_ + (1 - b1) * gf
                v2 = b2 * v_ + (1 - b2) * gf * gf
                mhat = m2 / bc1
                vhat = v2 / bc2
                upd = mhat / (jnp.sqrt(vhat) + eps)
                if decoupled and wd != 0.0:
                    upd = upd + wd * dm * pf
                new_p.append((pf - lr * upd).astype(p.dtype))
                new_m.append(m2)
                new_v.append(v2)
            return tuple(new_p) + tuple(new_m) + tuple(new_v)

        grads = [p.grad for p in params]
        outs = dispatch(
            "adamw" if self._decoupled else "adam", impl,
            (self._lr_tensor, self._step_count) + tuple(params) +
            tuple(grads) + tuple(m) + tuple(v),
            dict(b1=b1, b2=b2, eps=float(self._epsilon), wd=wd,
                 decoupled=self._decoupled, n=len(params), mask=decay_mask),
            differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(m, outs[n:2 * n]):
            t._inplace_update(new._value)
        for t, new in zip(v, outs[2 * n:]):
            t._inplace_update(new._value)


class Adam(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, decoupled=False, **kw)


class AdamW(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, decoupled=True,
                         apply_decay_param_fun=apply_decay_param_fun, **kw)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _apply(self, params):
        m = [self._acc("moment", p) for p in params]
        u = [self._acc("inf_norm", p) for p in params]

        def impl(lr, t, *pgmu, b1, b2, eps, n):
            ps, gs = pgmu[:n], pgmu[n:2 * n]
            ms, us = pgmu[2 * n:3 * n], pgmu[3 * n:]
            tf = (t + 1).astype(jnp.float32)
            bc1 = 1.0 - jnp.power(b1, tf)
            outs_p, outs_m, outs_u = [], [], []
            for p, g, m_, u_ in zip(ps, gs, ms, us):
                gf = g.astype(jnp.float32)
                m2 = b1 * m_ + (1 - b1) * gf
                u2 = jnp.maximum(b2 * u_, jnp.abs(gf))
                upd = m2 / bc1 / (u2 + eps)
                outs_p.append((p.astype(jnp.float32) -
                               lr * upd).astype(p.dtype))
                outs_m.append(m2)
                outs_u.append(u2)
            return tuple(outs_p) + tuple(outs_m) + tuple(outs_u)

        grads = [p.grad for p in params]
        outs = dispatch("adamax", impl,
                        (self._lr_tensor, self._step_count) + tuple(params) +
                        tuple(grads) + tuple(m) + tuple(u),
                        dict(b1=float(self._beta1), b2=float(self._beta2),
                             eps=float(self._epsilon), n=len(params)),
                        differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(m, outs[n:2 * n]):
            t._inplace_update(new._value)
        for t, new in zip(u, outs[2 * n:]):
            t._inplace_update(new._value)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _apply(self, params):
        acc = [self._acc("moment", p, self._init_acc) for p in params]
        wd = self._decay_coeff()

        def impl(lr, *pga, eps, wd, n):
            ps, gs, accs = pga[:n], pga[n:2 * n], pga[2 * n:]
            outs_p, outs_a = [], []
            for p, g, a in zip(ps, gs, accs):
                gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                a2 = a + gf * gf
                outs_p.append((p.astype(jnp.float32) -
                               lr * gf / (jnp.sqrt(a2) + eps)).astype(
                                   p.dtype))
                outs_a.append(a2)
            return tuple(outs_p) + tuple(outs_a)

        grads = [p.grad for p in params]
        outs = dispatch("adagrad", impl,
                        (self._lr_tensor,) + tuple(params) + tuple(grads) +
                        tuple(acc),
                        dict(eps=float(self._epsilon), wd=wd,
                             n=len(params)), differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(acc, outs[n:]):
            t._inplace_update(new._value)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _apply(self, params):
        avg_sq = [self._acc("avg_squared_grad", p) for p in params]
        avg_up = [self._acc("avg_squared_update", p) for p in params]
        wd = self._decay_coeff()

        def impl(lr, *arrs, eps, rho, wd, n):
            ps, gs = arrs[:n], arrs[n:2 * n]
            sqs, ups = arrs[2 * n:3 * n], arrs[3 * n:]
            outs_p, outs_s, outs_u = [], [], []
            for p, g, s, u in zip(ps, gs, sqs, ups):
                gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                s2 = rho * s + (1 - rho) * gf * gf
                upd = jnp.sqrt(u + eps) / jnp.sqrt(s2 + eps) * gf
                u2 = rho * u + (1 - rho) * upd * upd
                outs_p.append((p.astype(jnp.float32) -
                               lr * upd).astype(p.dtype))
                outs_s.append(s2)
                outs_u.append(u2)
            return tuple(outs_p) + tuple(outs_s) + tuple(outs_u)

        grads = [p.grad for p in params]
        outs = dispatch("adadelta", impl,
                        (self._lr_tensor,) + tuple(params) + tuple(grads) +
                        tuple(avg_sq) + tuple(avg_up),
                        dict(eps=float(self._epsilon), rho=float(self._rho),
                             wd=wd, n=len(params)), differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(avg_sq, outs[n:2 * n]):
            t._inplace_update(new._value)
        for t, new in zip(avg_up, outs[2 * n:]):
            t._inplace_update(new._value)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _apply(self, params):
        ms = [self._acc("mean_square", p) for p in params]
        mom = [self._acc("momentum", p) for p in params]
        mg = [self._acc("mean_grad", p) for p in params]
        wd = self._decay_coeff()

        def impl(lr, *arrs, rho, eps, mu, centered, wd, n):
            ps, gs = arrs[:n], arrs[n:2 * n]
            mss, moms, mgs = arrs[2 * n:3 * n], arrs[3 * n:4 * n], \
                arrs[4 * n:]
            o_p, o_ms, o_mom, o_mg = [], [], [], []
            for p, g, s, v, a in zip(ps, gs, mss, moms, mgs):
                gf = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                s2 = rho * s + (1 - rho) * gf * gf
                if centered:
                    a2 = rho * a + (1 - rho) * gf
                    denom = jnp.sqrt(s2 - a2 * a2 + eps)
                else:
                    a2 = a
                    denom = jnp.sqrt(s2 + eps)
                v2 = mu * v + lr * gf / denom
                o_p.append((p.astype(jnp.float32) - v2).astype(p.dtype))
                o_ms.append(s2)
                o_mom.append(v2)
                o_mg.append(a2)
            return tuple(o_p) + tuple(o_ms) + tuple(o_mom) + tuple(o_mg)

        grads = [p.grad for p in params]
        outs = dispatch("rmsprop", impl,
                        (self._lr_tensor,) + tuple(params) + tuple(grads) +
                        tuple(ms) + tuple(mom) + tuple(mg),
                        dict(rho=float(self._rho), eps=float(self._epsilon),
                             mu=float(self._momentum),
                             centered=self._centered, wd=wd, n=len(params)),
                        differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(ms, outs[n:2 * n]):
            t._inplace_update(new._value)
        for t, new in zip(mom, outs[2 * n:3 * n]):
            t._inplace_update(new._value)
        for t, new in zip(mg, outs[3 * n:]):
            t._inplace_update(new._value)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply(self, params):
        m = [self._acc("moment1", p) for p in params]
        v = [self._acc("moment2", p) for p in params]
        mask = tuple(0.0 if (self._exclude_fn and self._exclude_fn(p))
                     else 1.0 for p in params)

        def impl(lr, t, *arrs, b1, b2, eps, wd, n, mask):
            ps, gs = arrs[:n], arrs[n:2 * n]
            ms, vs = arrs[2 * n:3 * n], arrs[3 * n:]
            tf = (t + 1).astype(jnp.float32)
            bc1 = 1.0 - jnp.power(b1, tf)
            bc2 = 1.0 - jnp.power(b2, tf)
            o_p, o_m, o_v = [], [], []
            for p, g, m_, v_, dm in zip(ps, gs, ms, vs, mask):
                pf = p.astype(jnp.float32)
                gf = g.astype(jnp.float32)
                m2 = b1 * m_ + (1 - b1) * gf
                v2 = b2 * v_ + (1 - b2) * gf * gf
                r = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps) + \
                    wd * dm * pf
                w_norm = jnp.linalg.norm(pf)
                r_norm = jnp.linalg.norm(r)
                ratio = jnp.where((w_norm > 0) & (r_norm > 0),
                                  w_norm / r_norm, 1.0)
                o_p.append((pf - lr * ratio * r).astype(p.dtype))
                o_m.append(m2)
                o_v.append(v2)
            return tuple(o_p) + tuple(o_m) + tuple(o_v)

        grads = [p.grad for p in params]
        outs = dispatch("lamb", impl,
                        (self._lr_tensor, self._step_count) + tuple(params) +
                        tuple(grads) + tuple(m) + tuple(v),
                        dict(b1=float(self._beta1), b2=float(self._beta2),
                             eps=float(self._epsilon),
                             wd=float(self._lamb_wd), n=len(params),
                             mask=mask), differentiable=False)
        n = len(params)
        for p, new in zip(params, outs[:n]):
            p._inplace_update(new._value)
        for t, new in zip(m, outs[n:2 * n]):
            t._inplace_update(new._value)
        for t, new in zip(v, outs[2 * n:]):
            t._inplace_update(new._value)
