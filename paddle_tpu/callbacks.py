"""paddle.callbacks: hapi training callbacks.

Reference parity: `python/paddle/hapi/callbacks.py` (Callback base,
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL
[UNVERIFIED — empty reference mount]).  The hook protocol is identical
(`on_{train,eval,predict}_{begin,end}`, `on_epoch_{begin,end}`,
`on_{train,eval}_batch_{begin,end}`); paddle.Model.fit drives them.
VisualDLCallback logs scalars to a JSONL file (VisualDL itself is an
external package; the artifact is importable into TensorBoard via the
jax profiler instead).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "CallbackList", "ReduceLROnPlateau"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # hook protocol — subclasses override what they need
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass

    # EarlyStopping signals through this flag
    stop_training = False


class CallbackList:
    def __init__(self, callbacks=None, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            if params is not None:  # never wipe params fit installed
                c.set_params(params)

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(c.stop_training for c in self.callbacks)


class ProgBarLogger(Callback):
    """Prints loss/metrics every `log_freq` train steps."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if not self.verbose or step % self.log_freq:
            return
        logs = logs or {}
        parts = [f"step {step}"]
        for k, v in logs.items():
            try:
                parts.append(f"{k}={float(np.asarray(v)):.4f}")
            except Exception:
                pass
        print(f"Epoch {self._epoch + 1}: " + " ".join(parts), flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1} done in "
                  f"{time.time() - self._t0:.1f}s", flush=True)


class ModelCheckpoint(Callback):
    """Saves model+optimizer state every `save_freq` epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def _save(self, tag):
        if self.save_dir is None or self.model is None:
            return
        os.makedirs(self.save_dir, exist_ok=True)
        path = os.path.join(self.save_dir, str(tag))
        self.model.save(path)

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self._save(epoch)

    def on_train_end(self, logs=None):
        self._save("final")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _sched(self):
        from .optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop training when `monitor` stops improving."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline
        self.stop_training = False

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]))
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            self.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: no {self.monitor} improvement "
                      f"for {self.wait} evals; stopping", flush=True)

    # monitors ONLY eval results (the reference's contract: pass
    # eval_data to fit).  on_epoch_end intentionally not overridden —
    # fit fires both hooks each epoch and a second delivery here would
    # double-count toward patience.


class ReduceLROnPlateau(Callback):
    """Multiply LR by `factor` after `patience` evals w/o improvement."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 min_lr=0.0, min_delta=1e-4, mode="auto", verbose=1,
                 cooldown=0):
        super().__init__()
        self.monitor, self.factor = monitor, factor
        self.patience, self.min_lr = patience, min_lr
        self.min_delta = min_delta
        self.mode = ("max" if "acc" in monitor else "min") \
            if mode == "auto" else mode
        self.verbose = verbose
        self.cooldown = cooldown
        self._cool = 0
        self.wait = 0
        self.best = None

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]))
        better = self.best is None or (
            cur < self.best - self.min_delta if self.mode == "min"
            else cur > self.best + self.min_delta)
        if self._cool > 0:
            self._cool -= 1
            self.wait = 0
        if better:
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                new_lr = max(float(opt.get_lr()) * self.factor,
                             self.min_lr)
                opt.set_lr(new_lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {new_lr:g}",
                          flush=True)
            self.wait = 0
            self._cool = self.cooldown

    # like EarlyStopping: eval-only monitoring, single delivery


class VisualDL(Callback):
    """Scalar logger: JSONL records {tag, step, value, wall_time} under
    log_dir (readable by any dashboard; VisualDL itself is external)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def _write(self, tag, value, step):
        if self._f is None:
            return
        try:
            rec = {"tag": tag, "step": step,
                   "value": float(np.asarray(value)),
                   "wall_time": time.time()}
        except Exception:
            return
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            self._write(f"train/{k}", v, self._step)

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            self._write(f"eval/{k}", v, self._step)

    def on_train_end(self, logs=None):
        if self._f is not None:
            self._f.close()
            self._f = None
