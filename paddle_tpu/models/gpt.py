"""GPT decoder-only LM (BASELINE.md config #4: GPT-3 1.3B class).

Reference parity: `paddlenlp/transformers/gpt/modeling.py` [UNVERIFIED —
empty reference mount].  TPU-native notes: attention routes through
F.scaled_dot_product_attention → the Pallas flash kernel on TPU; the LM
loss uses the fused softmax-xent path via F.cross_entropy; recompute
(jax.checkpoint) can wrap each block via `recompute=True`.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F
from .generation import GenerationMixin


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0      # 0 → 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.0
    use_flash_attention: bool = True
    use_recompute: bool = False
    tie_word_embeddings: bool = True
    # lax.scan over stacked block weights (nn/layer/scanned.py):
    # compile time O(1) in depth; only the no-cache training path
    use_scan_layers: bool = False

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size


# 1.3B preset (GPT-3 XL shape) used by bench configs
GPT_1P3B = dict(vocab_size=50304, hidden_size=2048, num_hidden_layers=24,
                num_attention_heads=16, max_position_embeddings=2048)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.use_flash = cfg.use_flash_attention
        self.qkv_proj = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size)
        self.out_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, x, cache=None, use_cache=False):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        # multi-LoRA serving (inference/serving/lora): per-q-block
        # adapter deltas ride the segmented SGMV epilogue after each
        # projection; rows without an adapter hit the zero segment
        lora = getattr(cache, "lora", None) if cache is not None else None
        if lora is not None and lora.active(self.qkv_proj):
            qkv = lora.apply(qkv, x, self.qkv_proj)
        qkv = paddle.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = paddle.unbind(qkv, axis=2)     # each [b, s, nh, hd]
        if cache is not None and hasattr(cache, "attend"):
            # paged serving cache (inference/serving): the layer view
            # scatters K/V into the block pool and attends through the
            # block tables; dense semantics below stay untouched
            attn = cache.attend(q, k, v, use_flash=self.use_flash)
            attn = paddle.reshape(attn, [b, s, h])
            out = self.out_proj(attn)
            if lora is not None and lora.active(self.out_proj):
                out = lora.apply(out, attn, self.out_proj)
            if use_cache:
                return out, cache
            return out
        if cache is not None:
            # decode: extend K/V with the cached prefix; the SDPA causal
            # mask is bottom-right aligned, so new rows see everything
            k = paddle.concat([cache[0], k], axis=1)
            v = paddle.concat([cache[1], v], axis=1)
        from ..nn.functional.flash_attention import sdp_kernel
        # enable_flash=True is exactly the automatic-selection default
        with sdp_kernel(enable_flash=self.use_flash):
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = paddle.reshape(out, [b, s, h])
        out = self.out_proj(out)
        if use_cache:
            return out, (k, v)
        return out


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)

    def forward(self, x, lora=None):
        # fc1's bias+gelu fold into the matmul epilogue on TPU
        w_q = getattr(self.fc1, "weight_q", None)
        if w_q is not None:
            h = F.linear_act_int8(x, w_q, self.fc1.weight_scale,
                                  self.fc1.bias, act="gelu_tanh")
        elif lora is not None and lora.active(self.fc1):
            # the activation defers past the LoRA delta — the SGMV
            # epilogue computes act(z + delta) in one fused pass
            z = F.linear(x, self.fc1.weight, self.fc1.bias)
            h = lora.apply(z, x, self.fc1, act="gelu_tanh")
        else:
            h = F.linear_act(x, self.fc1.weight, self.fc1.bias,
                             act="gelu_tanh")
        y = self.fc2(h)
        if lora is not None and lora.active(self.fc2):
            y = lora.apply(y, h, self.fc2)
        return y


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = GPTMLP(cfg)
        # GPT-2 style residual dropout (config default 0.0 — a no-op
        # unless the user opts in; scan_layers requires it stay 0)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None, use_cache=False):
        lora = getattr(cache, "lora", None) if cache is not None else None
        if use_cache:
            a, new_cache = self.attn(self.ln_1(x), cache, True)
            x = x + self.dropout(a)
            x = x + self.dropout(self.mlp(self.ln_2(x), lora=lora))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x), cache))
        x = x + self.dropout(self.mlp(self.ln_2(x), lora=lora))
        return x


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size)
        self.h = nn.LayerList([GPTBlock(cfg)
                               for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        self._recompute = cfg.use_recompute

    def forward(self, input_ids, cache=None, use_cache=False):
        b, s = input_ids.shape
        if cache is not None and getattr(cache, "position_ids", None) \
                is not None:
            # paged serving cache: rows sit at different absolute
            # positions, so the engine supplies them per step
            pos = cache.position_ids
        else:
            past = 0 if cache is None else cache[0][0].shape[1]
            pos = paddle.arange(past, past + s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        drop_active = (self.training
                       and self.config.hidden_dropout_prob > 0)
        # the memory guard's ladder can flip recompute on globally
        # without touching the model config
        from ..memory.guard import remat_enabled
        use_remat = self._recompute or remat_enabled()
        if (self.config.use_scan_layers and cache is None
                and not use_cache and not drop_active):
            from ..nn.layer import scanned
            x = scanned.scan_layer_stack(self.h, x,
                                         remat=use_remat)
            return self.ln_f(x)
        if (self.config.use_scan_layers and drop_active
                and not getattr(self, "_scan_fallback_warned", False)):
            self._scan_fallback_warned = True
            import logging
            logging.getLogger("paddle_tpu.models").warning(
                "use_scan_layers requires dropout == 0 (per-layer rng "
                "is not threaded through the scanned stack); falling "
                "back to the unrolled layer loop")
        new_caches = []
        for i, blk in enumerate(self.h):
            layer_cache = None if cache is None else cache[i]
            if use_cache:
                x, c = blk(x, layer_cache, True)
                new_caches.append(c)
            elif use_remat and layer_cache is None:
                from ..distributed.fleet.recompute import recompute
                x = recompute(blk, x)
            else:
                # a supplied cache participates even when the caller
                # doesn't want an updated one back
                x = blk(x, layer_cache)
        x = self.ln_f(x)
        if use_cache:
            return x, new_caches
        return x


class GPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, cache=None, use_cache=False):
        if use_cache:
            hidden, new_cache = self.gpt(input_ids, cache, True)
        else:
            hidden = self.gpt(input_ids, cache)
            new_cache = None
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = paddle.matmul(hidden, self.gpt.wte.weight,
                                   transpose_y=True)
        if use_cache:
            return logits, new_cache
        return logits


class GPTPretrainingCriterion(nn.Layer):
    """Shifted next-token LM loss (ignore_index=-100 for padding)."""

    def forward(self, logits, labels):
        b, s, v = logits.shape
        logits = paddle.reshape(logits[:, :-1, :], [-1, v])
        labels = paddle.reshape(labels[:, 1:], [-1])
        return F.cross_entropy(logits, labels, reduction="mean")
