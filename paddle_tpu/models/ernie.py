"""ERNIE: Paddle's flagship pretrained-LM family.

Reference parity: `paddlenlp/transformers/ernie/modeling.py`
(ErnieModel = BERT-style encoder + task-type embeddings + pooler;
ErnieForSequenceClassification / ErnieForMaskedLM heads [UNVERIFIED —
empty reference mount]).  Reuses this package's Bert blocks — the
architectures differ only in the task-type embedding term and the
pooled [CLS] head, so the TPU-native encoder (Pallas attention via the
functional layer, XLA-fused residual blocks) is shared.
"""
from __future__ import annotations

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F
from .bert import BertConfig, BertEmbeddings, BertLayer, TiedMLMHead

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForMaskedLM",
           "ErnieForSequenceClassification"]


class ErnieConfig(BertConfig):
    def __init__(self, task_type_vocab_size=3, use_task_id=True,
                 num_labels=2, **kw):
        super().__init__(**kw)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id
        self.num_labels = num_labels


class ErnieEmbeddings(BertEmbeddings):
    """Bert embeddings + the ERNIE task-type embedding term, summed
    BEFORE the shared LayerNorm (reference order: LN(word + pos +
    token_type + task_type))."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__(cfg)
        self.task_type_embeddings = None
        if cfg.use_task_id:
            self.task_type_embeddings = nn.Embedding(
                cfg.task_type_vocab_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None):
        b, s = input_ids.shape
        pos = paddle.arange(s, dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(pos))
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = paddle.zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.layer_norm(x)


class ErnieModel(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        self.encoder = nn.LayerList(
            [BertLayer(cfg) for _ in range(cfg.num_hidden_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids, task_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForMaskedLM(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.cls = TiedMLMHead(cfg)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None, labels=None):
        hidden, _ = self.ernie(input_ids, token_type_ids,
                               task_type_ids, attn_mask)
        return self.cls(hidden,
                        self.ernie.embeddings.word_embeddings.weight,
                        labels)


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig, dropout_prob=0.1):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = nn.Dropout(dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, cfg.num_labels)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attn_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               task_type_ids, attn_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        loss = F.cross_entropy(logits, paddle.reshape(labels, [-1]),
                               reduction="mean")
        return loss, logits
