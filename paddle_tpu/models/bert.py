"""BERT encoder + MLM head (BASELINE.md config #3: BERT-base MLM).

Reference parity: `paddlenlp/transformers/bert/modeling.py` [UNVERIFIED —
empty reference mount].
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # Paddle-parity defaults (paddlenlp BertConfig): dropout on the
    # embeddings, each sublayer output, and the attention probs.  The
    # static Executor threads the generator state per step, so dropout
    # works in static programs and the fused run_steps loop.
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    # lax.scan over stacked layer weights: compile time O(1) in depth
    # (nn/layer/scanned.py); numerics identical to the unrolled loop.
    # Requires dropout == 0 (per-layer rng inside the scanned stack is
    # not threaded) — BertModel falls back to the unrolled loop loudly.
    use_scan_layers: bool = False


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = paddle.arange(s, dtype="int64")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.attn_drop_p = cfg.attention_probs_dropout_prob
        self.qkv = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size)
        self.out = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = paddle.reshape(self.qkv(x),
                             [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = paddle.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_drop_p,
            training=self.training)
        return self.out(paddle.reshape(out, [b, s, h]))


class BertLayer(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(cfg)
        self.ln1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.ln2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        # post-norm: residual adds fuse into the LN kernel; fc1's
        # bias+gelu fold into the matmul epilogue (both TPU-gated)
        x = self.ln1.forward_fused(
            self.dropout(self.attention(x, attn_mask)), x)
        h = F.linear_act(x, self.fc1.weight, self.fc1.bias,
                         act="gelu_tanh")
        x = self.ln2.forward_fused(self.dropout(self.fc2(h)), x)
        return x


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = nn.LayerList([BertLayer(cfg)
                                     for _ in range(cfg.num_hidden_layers)])

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        cfg = self.config
        drop_active = self.training and (
            cfg.hidden_dropout_prob > 0
            or cfg.attention_probs_dropout_prob > 0)
        if cfg.use_scan_layers and attn_mask is None:
            if drop_active:
                if not getattr(self, "_scan_fallback_warned", False):
                    self._scan_fallback_warned = True
                    import logging
                    logging.getLogger("paddle_tpu.models").warning(
                        "use_scan_layers requires dropout == 0 "
                        "(per-layer rng is not threaded through the "
                        "scanned stack); falling back to the unrolled "
                        "layer loop")
            else:
                from ..nn.layer import scanned
                return scanned.scan_layer_stack(self.encoder, x)
        for layer in self.encoder:
            x = layer(x, attn_mask)
        return x


class TiedMLMHead(nn.Layer):
    """transform → gelu → LN → logits tied to the word embedding; the
    shared masked-LM head for BERT-family encoders (ERNIE reuses it)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.ln = nn.LayerNorm(cfg.hidden_size,
                               epsilon=cfg.layer_norm_eps)

    def forward(self, hidden, word_embedding_weight, labels=None):
        hidden = self.ln(F.linear_act(
            hidden, self.transform.weight, self.transform.bias,
            act="gelu_tanh"))
        logits = paddle.matmul(hidden, word_embedding_weight,
                               transpose_y=True)
        if labels is None:
            return logits
        v = logits.shape[-1]
        loss = F.cross_entropy(paddle.reshape(logits, [-1, v]),
                               paddle.reshape(labels, [-1]),
                               ignore_index=-100, reduction="mean")
        return loss, logits


class BertForMaskedLM(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.cls = TiedMLMHead(cfg)

    def forward(self, input_ids, token_type_ids=None, labels=None):
        hidden = self.bert(input_ids, token_type_ids)
        return self.cls(hidden,
                        self.bert.embeddings.word_embeddings.weight,
                        labels)
