"""Model families matching the reference's benchmark configs.

Reference parity: GPT/BERT/LLaMA live in the PaddleNLP ecosystem
(`paddlenlp/transformers/{gpt,bert,llama}/modeling.py` [UNVERIFIED — the
reference mount is empty; BASELINE.md configs 3-5 name these models]);
vision models live in `python/paddle/vision/models` (already in
paddle_tpu.vision).  These are the flagship LM families the benchmarks
and the multichip dryrun drive.
"""
from .gpt import GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion
from .bert import BertConfig, BertModel, BertForMaskedLM
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM
from .ernie import (ErnieConfig, ErnieModel, ErnieForMaskedLM,
                    ErnieForSequenceClassification)
from .moe_gpt import (MoEGPTConfig, MoEGPTModel, MoEGPTForCausalLM,
                      MoEGPTPretrainingCriterion)
from .generation import GenerationMixin, generate

__all__ = [
    "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
    "BertConfig", "BertModel", "BertForMaskedLM",
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "ErnieConfig", "ErnieModel", "ErnieForMaskedLM",
    "ErnieForSequenceClassification",
    "MoEGPTConfig", "MoEGPTModel", "MoEGPTForCausalLM",
    "MoEGPTPretrainingCriterion", "GenerationMixin", "generate",
]
