"""LLaMA decoder LM (BASELINE.md config #5: LLaMA-2 7B class).

Reference parity: `paddlenlp/transformers/llama/modeling.py` [UNVERIFIED —
empty reference mount].  RMSNorm routes to the Pallas fused kernel on
TPU; attention to the Pallas flash kernel; rotary tables are fixed
buffers (host-precomputed, folded into the compiled step as constants).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F
from .generation import GenerationMixin


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 0     # 0 → same as num_attention_heads
    intermediate_size: int = 11008
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    use_recompute: bool = False

    def __post_init__(self):
        if not self.num_key_value_heads:
            self.num_key_value_heads = self.num_attention_heads


# 7B preset
LLAMA_7B = dict(vocab_size=32000, hidden_size=4096, num_hidden_layers=32,
                num_attention_heads=32, intermediate_size=11008,
                max_position_embeddings=4096)


def _rope_tables(head_dim, max_pos, theta):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64)
                           / head_dim))
    t = np.arange(max_pos, dtype=np.float64)
    freqs = np.outer(t, inv)                       # [S, D/2]
    emb = np.concatenate([freqs, freqs], axis=-1)  # [S, D]
    return (np.cos(emb).astype(np.float32), np.sin(emb).astype(np.float32))


def _rotate_half(x):
    d = x.shape[-1]
    x1 = x[..., : d // 2]
    x2 = x[..., d // 2:]
    return paddle.concat([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q/k: [b, s, h, d]; cos/sin: [s, d] broadcast over batch/heads."""
    cos = paddle.unsqueeze(paddle.unsqueeze(cos, 0), 2)   # [1, s, 1, d]
    sin = paddle.unsqueeze(paddle.unsqueeze(sin, 0), 2)
    q2 = q * cos + _rotate_half(q) * sin
    k2 = k * cos + _rotate_half(k) * sin
    return q2, k2


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.q_proj = nn.Linear(cfg.hidden_size,
                                self.num_heads * self.head_dim,
                                bias_attr=False)
        self.k_proj = nn.Linear(cfg.hidden_size,
                                self.num_kv_heads * self.head_dim,
                                bias_attr=False)
        self.v_proj = nn.Linear(cfg.hidden_size,
                                self.num_kv_heads * self.head_dim,
                                bias_attr=False)
        self.o_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                bias_attr=False)

    def forward(self, x, cos, sin, cache=None, use_cache=False):
        b, s, _ = x.shape
        q = paddle.reshape(self.q_proj(x),
                           [b, s, self.num_heads, self.head_dim])
        k = paddle.reshape(self.k_proj(x),
                           [b, s, self.num_kv_heads, self.head_dim])
        v = paddle.reshape(self.v_proj(x),
                           [b, s, self.num_kv_heads, self.head_dim])
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        if cache is not None:
            # cache holds PRE-GQA (kv-head) keys/values, already rotated
            k = paddle.concat([cache[0], k], axis=1)
            v = paddle.concat([cache[1], v], axis=1)
        new_cache = (k, v) if use_cache else None
        if self.num_kv_heads != self.num_heads:   # GQA: repeat kv heads
            rep = self.num_heads // self.num_kv_heads
            k = paddle.repeat_interleave(k, rep, axis=2)
            v = paddle.repeat_interleave(v, rep, axis=2)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = self.o_proj(paddle.reshape(out, [b, s, -1]))
        if use_cache:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.gate_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                   bias_attr=False)
        self.up_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                 bias_attr=False)
        self.down_proj = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                   bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(
            cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cos, sin, cache=None, use_cache=False):
        if use_cache:
            a, new_cache = self.self_attn(self.input_layernorm(x), cos,
                                          sin, cache, True)
            x = x + a
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, cache)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.config = cfg
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(cfg)
                                    for _ in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = _rope_tables(head_dim, cfg.max_position_embeddings,
                                cfg.rope_theta)
        self.register_buffer("rope_cos", paddle.to_tensor(cos))
        self.register_buffer("rope_sin", paddle.to_tensor(sin))
        self._recompute = cfg.use_recompute

    def forward(self, input_ids, cache=None, use_cache=False):
        b, s = input_ids.shape
        past = 0 if cache is None else cache[0][0].shape[1]
        x = self.embed_tokens(input_ids)
        cos = self.rope_cos[past:past + s]
        sin = self.rope_sin[past:past + s]
        new_caches = []
        for i, layer in enumerate(self.layers):
            layer_cache = None if cache is None else cache[i]
            if use_cache:
                x, c = layer(x, cos, sin, layer_cache, True)
                new_caches.append(c)
            elif self._recompute and layer_cache is None:
                from ..distributed.fleet.recompute import recompute
                x = recompute(layer, x, cos, sin)
            else:
                # a supplied cache participates even when the caller
                # doesn't want an updated one back
                x = layer(x, cos, sin, layer_cache)
        x = self.norm(x)
        if use_cache:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.llama = LlamaModel(cfg)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, labels=None, cache=None,
                use_cache=False):
        if use_cache:
            hidden, new_cache = self.llama(input_ids, cache, True)
            return self.lm_head(hidden), new_cache
        hidden = self.llama(input_ids, cache)
        logits = self.lm_head(hidden)
        if labels is None:
            return logits
        v = logits.shape[-1]
        loss = F.cross_entropy(
            paddle.reshape(logits[:, :-1, :], [-1, v]),
            paddle.reshape(labels[:, 1:], [-1]), reduction="mean")
        return loss, logits
