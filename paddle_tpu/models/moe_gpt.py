"""MoE GPT: the bundled Mixture-of-Experts decoder LM.

Same skeleton as `models/gpt.py` (blocks reuse `GPTAttention`, so the
serving engine's paged `cache.attend` path and the flash kernel route
identically), with every block's dense MLP replaced by a dropless
top-k expert MLP:

  * the router scores each token against ``num_experts`` experts and
    keeps the top-k (renormalized — the weights of the kept experts
    sum to 1, so a model whose experts are initialized identically is
    numerically the dense model: the parity tests' iso-config twin);
  * routing is DROPLESS (`distributed.auto_parallel.moe_dispatch`):
    every assignment gets a row in a block-aligned grouped buffer —
    imbalance costs padding, never quality;
  * expert FFNs are STACKED parameters ``w1 [E, H, I]`` / ``w2 [E, I,
    H]`` computed by the grouped-expert Pallas matmul
    (`ops.pallas_grouped`, XLA composite fallback when the gate is
    off);
  * under a mesh with an ``ep`` axis the stacked experts shard over it
    and each device computes only its own experts' blocks inside a
    ``shard_map`` island (`MOE_GPT_RULES` carries the ``P("ep", ...)``
    specs for the SPMD executor; `MeshPlan.shrink` re-legalizes them
    when ``ep`` collapses on elastic recovery).

Per-token routing is row-independent, so serving's ragged batch
packing never changes a token's expert assignment — moe_gpt serves
through the unified ragged step like any dense GPT.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F  # noqa: F401 (criterion parity imports)
from ..nn import initializer as I
from .generation import GenerationMixin
from .gpt import GPTAttention, GPTConfig, GPTPretrainingCriterion

__all__ = [
    "MoEGPTConfig", "MoEMLP", "MoEGPTBlock", "MoEGPTModel",
    "MoEGPTForCausalLM", "MoEGPTPretrainingCriterion",
]


@dataclass
class MoEGPTConfig(GPTConfig):
    num_experts: int = 4
    top_k: int = 2
    #: weight on the Switch-style load-balance auxiliary loss
    router_aux_weight: float = 0.01


def _moe_mlp_compute(x, rw, w1, b1, w2, b2, *, top_k, num_experts, act):
    """Pure dropless MoE MLP on flat tokens: route -> grouped expert
    FFN -> combine.  Returns (y [N, D], aux scalar, counts [E])."""
    from ..distributed.auto_parallel import moe_dispatch as md
    from ..ops import pallas_grouped as pg
    from ..ops.pallas_gate import pallas_enabled
    from ..ops.pallas_tiles import _demote_f64

    x, rw, w1, b1, w2, b2 = _demote_f64(x, rw, w1, b1, w2, b2)
    N = x.shape[0]
    logits = jnp.dot(x.astype(jnp.float32), rw.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)             # [N, E] f32
    topv, topi = jax.lax.top_k(probs, top_k)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)  # renormalize

    bm, nb, rows_total = pg.grouped_layout(N * top_k, num_experts,
                                           x.dtype)
    rows, gid, counts = md.dropless_plan(topi, num_experts, bm, nb)
    xd = md.dropless_dispatch(x, rows, top_k, rows_total)

    gmm = pg.grouped_linear_act if pallas_enabled("grouped_matmul") \
        else pg.grouped_linear_act_ref
    h = gmm(xd, w1, b1, block_group=gid, act=act)
    y_rows = gmm(h, w2, b2, block_group=gid, act="none")
    y = md.dropless_combine(y_rows, rows, topv)

    # Switch-style load balance: E * sum_e(frac_tokens_e * mean_prob_e)
    frac = counts.astype(jnp.float32) / max(N * top_k, 1)
    aux = num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
    return y.astype(x.dtype), aux, counts


def _moe_mlp_impl(x, rw, w1, b1, w2, b2, *, top_k, num_experts, act):
    y, aux, _ = _moe_mlp_compute(x, rw, w1, b1, w2, b2, top_k=top_k,
                                 num_experts=num_experts, act=act)
    return y, aux


def _make_ep_impl(mesh, axis):
    """Dropless MoE MLP with the stacked experts sharded over ``axis``:
    routing runs globally (tokens replicated), and each device computes
    only its experts' grouped blocks inside a shard_map island.

    Per-device grouped buffers are planned globally: assignments owned
    by other devices route to the device's null group (clamped to the
    kernel's zero expert), so every buffer has static shape and the
    scatter stays exact.  Bitwise, each assignment's expert FFN is the
    same per-block full-K dot as the unsharded path.
    """
    from ..distributed.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ep = int(mesh.shape[axis])

    def impl(x, rw, w1, b1, w2, b2, *, top_k, num_experts, act):
        from ..distributed.auto_parallel import moe_dispatch as md
        from ..ops import pallas_grouped as pg
        from ..ops.pallas_gate import pallas_enabled
        from ..ops.pallas_tiles import (_demote_f64, group_segments,
                                        num_group_blocks)

        x, rw, w1, b1, w2, b2 = _demote_f64(x, rw, w1, b1, w2, b2)
        e_loc = num_experts // ep
        N = x.shape[0]
        T = N * top_k
        logits = jnp.dot(x.astype(jnp.float32), rw.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, top_k)
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        e_flat = topi.reshape(-1).astype(jnp.int32)
        counts = jnp.zeros((num_experts,), jnp.int32).at[e_flat].add(1)

        bm = pg.grouped_block_rows(T, num_experts, x.dtype)
        # +1 group: each device's buffer carries a null group holding
        # the assignments other devices own
        nb = num_group_blocks(T, e_loc + 1, bm)
        xds, gids, row_maps = [], [], []
        for p in range(ep):
            in_p = (e_flat // e_loc) == p
            e_sub = jnp.where(in_p, e_flat - p * e_loc, e_loc)
            csub = jnp.zeros((e_loc + 1,), jnp.int32).at[e_sub].add(1)
            gid, offs = group_segments(csub, bm, nb)
            order = jnp.argsort(e_sub, stable=True)
            csum = jnp.cumsum(csub) - csub
            rank = jnp.arange(T, dtype=jnp.int32) - csum[e_sub[order]]
            rows = jnp.zeros((T,), jnp.int32).at[order].set(
                offs[e_sub[order]] + rank)
            xds.append(md.dropless_dispatch(x, rows, top_k, nb * bm))
            # dummy + tail groups both clamp to the kernel's zero expert
            gids.append(jnp.minimum(gid, e_loc))
            row_maps.append(rows)
        xd = jnp.stack(xds)                     # [P, rows_p, D]
        gid = jnp.stack(gids)                   # [P, nb]
        rows_stack = jnp.stack(row_maps)        # [P, T]

        gmm = pg.grouped_linear_act if pallas_enabled("grouped_matmul") \
            else pg.grouped_linear_act_ref

        def island(xd_l, gid_l, w1_l, b1_l, w2_l, b2_l):
            h = gmm(xd_l[0], w1_l, b1_l, block_group=gid_l[0], act=act)
            y = gmm(h, w2_l, b2_l, block_group=gid_l[0], act="none")
            return y[None]

        espec = P(axis)
        y_all = shard_map(
            island, mesh=mesh,
            in_specs=(espec, espec, espec, espec, espec, espec),
            out_specs=espec)(xd, gid, w1, b1, w2, b2)   # [P, rows_p, D]

        dev = e_flat // e_loc                            # [T]
        y_rows = y_all[dev, rows_stack[dev, jnp.arange(T)]]  # [T, D]
        y = jnp.einsum("nk,nkd->nd", topv,
                       y_rows.reshape(N, top_k, -1).astype(jnp.float32)
                       ).astype(x.dtype)
        frac = counts.astype(jnp.float32) / max(T, 1)
        aux = num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
        return y, aux

    return impl


class MoEMLP(nn.Layer):
    """Dropless top-k mixture-of-experts FFN with stacked parameters."""

    def __init__(self, cfg: MoEGPTConfig):
        super().__init__()
        H, Iv, E = (cfg.hidden_size, cfg.intermediate_size,
                    cfg.num_experts)
        self.num_experts = E
        self.top_k = cfg.top_k
        self.router = self.create_parameter(
            shape=[H, E], default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            shape=[E, H, Iv], default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter(
            shape=[E, Iv], is_bias=True,
            default_initializer=I.Constant(0.0))
        self.w2 = self.create_parameter(
            shape=[E, Iv, H], default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter(
            shape=[E, H], is_bias=True,
            default_initializer=I.Constant(0.0))
        self.aux_loss = None
        self._ep_impl = None
        self._ep_mesh = None

    def _impl_for_mesh(self):
        """Dense impl, or the ep-sharded island when the global mesh
        carries an expert axis that divides the expert count (the
        `MoELayer._maybe_ep_engine` discipline — re-evaluated whenever
        the mesh changes, so elastic shrink to ep=1 falls back)."""
        from ..distributed.env import global_mesh
        mesh = global_mesh()
        if mesh is self._ep_mesh and self._ep_impl is not None:
            return self._ep_impl
        impl = _moe_mlp_impl
        if mesh is not None:
            for cand in ("ep", "expert"):
                if (cand in mesh.axis_names and mesh.shape[cand] > 1
                        and self.num_experts % mesh.shape[cand] == 0):
                    impl = _make_ep_impl(mesh, cand)
                    break
        self._ep_mesh = mesh
        self._ep_impl = impl
        return impl

    def forward(self, x):
        from ..core.dispatch import dispatch
        orig_shape = list(x.shape)
        N = 1
        for s in orig_shape[:-1]:
            N *= s
        xf = paddle.reshape(x, [N, orig_shape[-1]])
        impl = self._impl_for_mesh()
        y, aux = dispatch(
            "moe_mlp_dropless", impl,
            (xf, self.router, self.w1, self.b1, self.w2, self.b2),
            dict(top_k=self.top_k, num_experts=self.num_experts,
                 act="gelu_tanh"))
        self.aux_loss = aux
        return paddle.reshape(y, orig_shape)


class MoEGPTBlock(nn.Layer):
    def __init__(self, cfg: MoEGPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = MoEMLP(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None, use_cache=False):
        if use_cache:
            a, new_cache = self.attn(self.ln_1(x), cache, True)
            x = x + self.dropout(a)
            x = x + self.dropout(self.mlp(self.ln_2(x)))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x), cache))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class MoEGPTModel(nn.Layer):
    def __init__(self, cfg: MoEGPTConfig):
        super().__init__()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size)
        self.h = nn.LayerList([MoEGPTBlock(cfg)
                               for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        self._recompute = cfg.use_recompute

    def forward(self, input_ids, cache=None, use_cache=False):
        b, s = input_ids.shape
        if cache is not None and getattr(cache, "position_ids", None) \
                is not None:
            pos = cache.position_ids
        else:
            past = 0 if cache is None else cache[0][0].shape[1]
            pos = paddle.arange(past, past + s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        from ..memory.guard import remat_enabled
        use_remat = self._recompute or remat_enabled()
        new_caches = []
        for i, blk in enumerate(self.h):
            layer_cache = None if cache is None else cache[i]
            if use_cache:
                x, c = blk(x, layer_cache, True)
                new_caches.append(c)
            elif use_remat and layer_cache is None:
                from ..distributed.fleet.recompute import recompute
                x = recompute(blk, x)
            else:
                x = blk(x, layer_cache)
        x = self.ln_f(x)
        if use_cache:
            return x, new_caches
        return x

    def aux_loss(self):
        """Sum of the blocks' router load-balance losses (None before
        the first forward)."""
        losses = [blk.mlp.aux_loss for blk in self.h
                  if blk.mlp.aux_loss is not None]
        if not losses:
            return None
        total = losses[0]
        for aux in losses[1:]:
            total = total + aux
        return total


class MoEGPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: MoEGPTConfig):
        super().__init__()
        self.config = cfg
        self.gpt = MoEGPTModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, cache=None, use_cache=False):
        if use_cache:
            hidden, new_cache = self.gpt(input_ids, cache, True)
        else:
            hidden = self.gpt(input_ids, cache)
            new_cache = None
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = paddle.matmul(hidden, self.gpt.wte.weight,
                                   transpose_y=True)
        if use_cache:
            return logits, new_cache
        return logits

    def aux_loss(self):
        return self.gpt.aux_loss()


class MoEGPTPretrainingCriterion(GPTPretrainingCriterion):
    """Shifted LM loss + weighted router load-balance auxiliary."""

    def __init__(self, model=None, aux_weight=None):
        super().__init__()
        self.model = model
        self.aux_weight = aux_weight

    def forward(self, logits, labels):
        loss = super().forward(logits, labels)
        if self.model is not None:
            aux = self.model.aux_loss()
            if aux is not None:
                w = self.aux_weight
                if w is None:
                    w = getattr(self.model.config, "router_aux_weight",
                                0.01)
                loss = loss + w * aux
        return loss
