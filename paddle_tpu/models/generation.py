"""Autoregressive generation for the causal-LM families.

Reference parity: PaddleNLP's `generation_utils.py` (greedy / sampling
decode loops [UNVERIFIED — empty reference mount]).

TPU note: models exposing `use_cache` (GPT/LLaMA) decode with a KV
cache — prefill once, then one-token steps reusing cached K/V, O(n)
per step.  The cache GROWS each step, so each length compiles its own
executable (bounded by max_length); the fixed-shape `lax.scan` decode
with a preallocated cache is the remaining upgrade for long
generations.  Models without `use_cache` fall back to full-sequence
recompute per step.
"""
from __future__ import annotations

import inspect

import numpy as np

__all__ = ["GenerationMixin", "generate"]


def _sample_logits(logits_row, do_sample, top_k, top_p, temperature,
                   rng):
    z = np.asarray(logits_row, np.float64)
    if not do_sample or temperature == 0.0:
        # temperature 0 means greedy (the conventional request), not
        # "skip scaling and sample at temperature 1"
        return int(z.argmax())
    if temperature is not None and temperature != 1.0:
        # None (HF-style "default") samples unscaled
        z = z / float(temperature)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    if top_k:
        k = min(int(top_k), len(p))  # clamp to vocab (HF semantics)
        kth = np.sort(p)[-k]
        p = np.where(p >= kth, p, 0.0)
        p /= p.sum()  # renormalize BEFORE nucleus filtering
    if top_p and top_p < 1.0:
        order = np.argsort(-p)
        cum = np.cumsum(p[order])
        # nucleus: smallest set whose cumulative mass REACHES top_p —
        # the boundary token is included (cum before it < top_p)
        cut = (cum - p[order]) < top_p
        mask = np.zeros_like(p, bool)
        mask[order[cut]] = True
        p = np.where(mask, p, 0.0)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def _model_max_positions(model):
    """Find max_position_embeddings on the model's config, if any."""
    for attr in ("config",):
        for obj in (model, getattr(model, "gpt", None),
                    getattr(model, "llama", None),
                    getattr(model, "model", None)):
            cfg = getattr(obj, attr, None) if obj is not None else None
            mp = getattr(cfg, "max_position_embeddings", None)
            if mp is not None:
                return int(mp)
    return None


def generate(model, input_ids, max_new_tokens=20, max_length=None,
             do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
             eos_token_id=None, pad_token_id=None, seed=None):
    """Decode continuation tokens; returns the full [B, S+T] ids."""
    import paddle_tpu as paddle
    from ..core.autograd import no_grad

    ids = np.asarray(input_ids.numpy()
                     if hasattr(input_ids, "numpy") else input_ids)
    if ids.ndim == 1:
        ids = ids[None, :]
    rng = np.random.default_rng(seed)
    if max_length is not None:
        max_new_tokens = max(0, int(max_length) - ids.shape[1])
    # never decode past the model's position table (silent clamping on
    # accelerators, a hard error on CPU's embedding bounds check)
    mp = _model_max_positions(model)
    if mp is not None:
        max_new_tokens = max(0, min(int(max_new_tokens),
                                    mp - ids.shape[1]))
    done = np.zeros(ids.shape[0], bool)
    cache = None
    use_cache = "use_cache" in inspect.signature(
        model.forward).parameters
    for step in range(int(max_new_tokens)):
        if use_cache:
            # KV-cache decode: feed only the new token after the prompt
            feed = ids if step == 0 else ids[:, -1:]
            with no_grad():
                out = model(paddle.to_tensor(feed.astype(np.int64)),
                            cache=cache, use_cache=True)
            logits, cache = out
        else:
            with no_grad():
                logits = model(paddle.to_tensor(ids.astype(np.int64)))
        if isinstance(logits, (tuple, list)):
            logits = logits[-1]
        last = np.asarray(logits.numpy())[:, -1, :]
        nxt = np.array([_sample_logits(last[b], do_sample, top_k, top_p,
                                       temperature, rng)
                        for b in range(ids.shape[0])], ids.dtype)
        if eos_token_id is not None:
            fill = eos_token_id if pad_token_id is None else pad_token_id
            nxt = np.where(done, fill, nxt)
            done |= nxt == eos_token_id
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
        if eos_token_id is not None and done.all():
            break
    return paddle.to_tensor(ids)


class GenerationMixin:
    def generate(self, input_ids, **kwargs):
        return generate(self, input_ids, **kwargs)
