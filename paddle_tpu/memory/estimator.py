"""Pre-flight HBM estimation from XLA's compiled memory analysis.

After ``jax.jit(...).lower(...).compile()`` the executable exposes
``memory_analysis()`` — XLA's own buffer-assignment totals (argument /
output / temp / generated-code bytes, plus input-output aliasing from
buffer donation).  That is the ground truth of what the program will
ask the allocator for, available BEFORE the first dispatch, so an
over-budget step can be refused while the error is still cheap.

The per-device budget comes from ``PADDLE_TPU_HBM_BUDGET`` (bytes, or
``512M`` / ``8G`` suffix form — the CPU-test knob) or, on real TPU,
the allocator's ``bytes_limit`` from ``memory_stats()``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .errors import HbmBudgetError

__all__ = ["MemoryEstimate", "ENV_HBM_BUDGET", "parse_bytes",
           "device_hbm_budget", "analyze_compiled", "named_buffer_sizes",
           "check_budget"]

ENV_HBM_BUDGET = "PADDLE_TPU_HBM_BUDGET"


@dataclass
class MemoryEstimate:
    """One compiled executable's predicted HBM footprint."""

    program: str = "<program>"
    argument_bytes: int = 0       # inputs incl. params + optimizer state
    output_bytes: int = 0
    temp_bytes: int = 0           # activations / scratch
    generated_code_bytes: int = 0
    alias_bytes: int = 0          # donated in→out aliasing (not doubled)
    # async step pipeline: extra copies of per-step feeds + outputs kept
    # live by the in-flight window (depth-1 un-synchronized steps)
    pipeline_bytes: int = 0
    pipeline_depth: int = 1
    # process-wide registered residents (e.g. the serving KV-cache block
    # pool) that live in HBM alongside this program but are NOT among
    # its arguments — see guard.register_resident()
    resident_bytes: int = 0
    # named resident buffers (params, opt state, feeds), largest first
    buffers: List[Tuple[str, int]] = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                + self.generated_code_bytes - self.alias_bytes
                + self.pipeline_bytes + self.resident_bytes)

    def top_buffers(self, k=5):
        """Top-k largest buffers, with XLA's temp/output totals ranked
        alongside the named residents so the report names the real
        hog even when it is activation scratch."""
        rows = list(self.buffers)
        if self.temp_bytes:
            rows.append(("<xla temp buffers (activations/scratch)>",
                         self.temp_bytes))
        if self.output_bytes:
            rows.append(("<xla outputs>", self.output_bytes))
        if self.pipeline_bytes:
            rows.append((f"<pipeline in-flight buffers "
                         f"(depth={self.pipeline_depth})>",
                         self.pipeline_bytes))
        rows.sort(key=lambda r: r[1], reverse=True)
        return rows[:k]

    def to_dict(self):
        gib = 2.0 ** 30
        return {
            "program": self.program,
            "argument_gb": round(self.argument_bytes / gib, 4),
            "output_gb": round(self.output_bytes / gib, 4),
            "temp_gb": round(self.temp_bytes / gib, 4),
            "generated_code_gb": round(self.generated_code_bytes / gib, 4),
            "alias_gb": round(self.alias_bytes / gib, 4),
            "pipeline_gb": round(self.pipeline_bytes / gib, 4),
            "pipeline_depth": self.pipeline_depth,
            "resident_gb": round(self.resident_bytes / gib, 4),
            "total_gb": round(self.total_bytes / gib, 4),
            "top_buffers": [
                {"name": n, "gb": round(b / gib, 4)}
                for n, b in self.top_buffers(5)],
        }


def parse_bytes(spec):
    """``"1073741824"`` | ``"512M"`` | ``"8G"`` | ``"1.5G"`` → bytes."""
    if spec is None:
        return None
    if isinstance(spec, (int, float)):
        return int(spec)
    s = str(spec).strip().upper()
    if not s:
        return None
    mult = 1
    for suffix, m in (("KIB", 2**10), ("MIB", 2**20), ("GIB", 2**30),
                      ("KB", 10**3), ("MB", 10**6), ("GB", 10**9),
                      ("K", 2**10), ("M", 2**20), ("G", 2**30),
                      ("B", 1)):
        if s.endswith(suffix):
            s = s[:-len(suffix)]
            mult = m
            break
    return int(float(s) * mult)


def device_hbm_budget(device=None):
    """The budget a program must fit: ``PADDLE_TPU_HBM_BUDGET`` if set
    (the CPU-test override), else the device allocator's ``bytes_limit``
    (real on TPU; absent on CPU → None, meaning 'no check')."""
    env = os.environ.get(ENV_HBM_BUDGET)
    if env:
        try:
            return parse_bytes(env)
        except ValueError:
            import logging
            logging.getLogger("paddle_tpu.memory").warning(
                "unparseable %s=%r; ignoring", ENV_HBM_BUDGET, env)
    from ..device import memory_stats
    limit = memory_stats(device).get("bytes_limit")
    return int(limit) if limit else None


def named_buffer_sizes(named_tensors):
    """[(name, Tensor-or-array)] → [(name, nbytes)] sorted desc.
    Duplicate underlying buffers (same object) are counted once."""
    out = []
    seen = set()
    for i, (name, t) in enumerate(named_tensors):
        if t is None:
            continue
        v = getattr(t, "_value", t)
        if id(v) in seen:
            continue
        seen.add(id(v))
        try:
            nbytes = int(v.size) * int(v.dtype.itemsize)
        except Exception:
            continue
        out.append((name or f"buffer_{i}", nbytes))
    out.sort(key=lambda r: r[1], reverse=True)
    return out


def analyze_compiled(compiled, program="<program>", named_buffers=None):
    """Build a MemoryEstimate from ``Compiled.memory_analysis()``.

    Returns None when the backend exposes no analysis (never raises —
    estimation must not break execution on exotic backends)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def _get(attr):
        try:
            return int(getattr(ma, attr, 0) or 0)
        except Exception:
            return 0

    return MemoryEstimate(
        program=program,
        argument_bytes=_get("argument_size_in_bytes"),
        output_bytes=_get("output_size_in_bytes"),
        temp_bytes=_get("temp_size_in_bytes"),
        generated_code_bytes=_get("generated_code_size_in_bytes"),
        alias_bytes=_get("alias_size_in_bytes"),
        buffers=list(named_buffers or []),
    )


def check_budget(estimate, budget=None, top_k=5, site="exec.oom"):
    """Raise HbmBudgetError iff ``estimate`` exceeds ``budget``.

    budget=None (no env override, no device limit) disables the check.
    Returns the estimate for chaining."""
    if estimate is None:
        return None
    if budget is None:
        budget = device_hbm_budget()
    if budget is not None and estimate.total_bytes > budget:
        raise HbmBudgetError(estimate.program, estimate, budget,
                             top_buffers=estimate.top_buffers(top_k),
                             site=site)
    return estimate
