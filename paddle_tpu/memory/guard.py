"""The memory guard: pre-flight budget checks, structured runtime OOM
diagnosis, and the policy object that arms the degradation ladder.

Three knobs:

  PADDLE_TPU_MEMORY_GUARD   "off" → no pre-flight check, raw re-raise
                            unset/"1"/"on" → pre-flight HbmBudgetError +
                              runtime TpuOutOfMemoryError (the default)
                            "ladder" → additionally install a default
                              GuardPolicy so guarded entry points retry
                              through the degradation ladder
  PADDLE_TPU_HBM_BUDGET     per-device budget for CPU tests (bytes or
                            512M/8G form); on TPU the allocator's real
                            bytes_limit is used when unset
  PADDLE_TPU_FAULT_PLAN     an ``exec.oom:oom`` event makes every
                            guarded dispatch raise a synthetic
                            RESOURCE_EXHAUSTED — OOM is injectable and
                            replayable like any PR-1 fault

Executors call ``preflight_check()`` right after AOT compilation and run
dispatch under ``oom_context()``; models consult ``remat_enabled()`` so
the ladder's first rung can flip recompute on globally without touching
layer configs.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading

from .. import observability as obs
from .errors import HbmBudgetError, TpuOutOfMemoryError
from .estimator import analyze_compiled, check_budget, device_hbm_budget

__all__ = ["ENV_MEMORY_GUARD", "guard_enabled", "guard_mode", "GuardPolicy",
           "set_guard_policy", "get_guard_policy", "preflight_check",
           "oom_context", "is_oom_error", "remat_enabled", "set_remat",
           "remat_scope", "last_estimate", "record_estimate",
           "register_resident", "unregister_resident", "resident_items",
           "host_resident_items"]

ENV_MEMORY_GUARD = "PADDLE_TPU_MEMORY_GUARD"
OOM_SITE = "exec.oom"

logger = logging.getLogger("paddle_tpu.memory")

_state = threading.local()
_policy = None
_policy_lock = threading.Lock()


def guard_mode():
    """"off" | "on" | "ladder" from PADDLE_TPU_MEMORY_GUARD."""
    v = os.environ.get(ENV_MEMORY_GUARD, "on").strip().lower()
    if v in ("0", "off", "false", "no", "disable", "disabled"):
        return "off"
    if v == "ladder":
        return "ladder"
    return "on"


def guard_enabled():
    return guard_mode() != "off"


class GuardPolicy:
    """What the guard may do when a program does not fit.

    rungs: ordered degradation ladder, a subset of
    ("remat", "grad_accum", "halve_batch").  ladder.py interprets them;
    ``taken`` records (rung, detail) for every rung actually engaged so
    degraded runs are visibly degraded (also asserted in tests).
    """

    DEFAULT_RUNGS = ("remat", "grad_accum", "halve_batch")

    def __init__(self, rungs=None, micro_batches=2, min_batch=1):
        rungs = tuple(rungs if rungs is not None else self.DEFAULT_RUNGS)
        unknown = set(rungs) - set(self.DEFAULT_RUNGS)
        if unknown:
            raise ValueError(f"GuardPolicy: unknown rungs {sorted(unknown)} "
                             f"(choose from {self.DEFAULT_RUNGS})")
        self.rungs = rungs
        self.micro_batches = int(micro_batches)
        self.min_batch = int(min_batch)
        self.taken = []

    def record(self, rung, detail=""):
        self.taken.append((rung, detail))
        obs.instant("memory.ladder", cat="memory", rung=rung,
                    detail=detail)
        logger.warning("memory guard: degradation rung %r engaged%s",
                       rung, f" ({detail})" if detail else "")

    def __repr__(self):
        return (f"GuardPolicy(rungs={self.rungs}, "
                f"micro_batches={self.micro_batches}, "
                f"min_batch={self.min_batch}, taken={self.taken})")


def set_guard_policy(policy):
    """Install (or clear, with None) the global GuardPolicy."""
    global _policy
    with _policy_lock:
        _policy = policy
    return policy


def get_guard_policy():
    """The installed GuardPolicy; under PADDLE_TPU_MEMORY_GUARD=ladder a
    default one is created on first use."""
    global _policy
    if _policy is None and guard_mode() == "ladder":
        with _policy_lock:
            if _policy is None:
                _policy = GuardPolicy()
    return _policy


# -- remat hook (ladder rung 1) ------------------------------------------
_remat = {"on": False}


def remat_enabled():
    """True when the ladder (or a user) turned on global recompute.
    Transformer/GPT blocks consult this alongside their own
    use_recompute config, so the ladder can flip it without rebuilds."""
    return _remat["on"]


def set_remat(on):
    prev = _remat["on"]
    _remat["on"] = bool(on)
    return prev


@contextlib.contextmanager
def remat_scope(on=True):
    prev = set_remat(on)
    try:
        yield
    finally:
        set_remat(prev)


# -- process-wide resident buffers --------------------------------------
# Long-lived device allocations that are NOT arguments of the program
# being pre-flighted (the serving engine's paged KV-cache block pool is
# the canonical one) still occupy HBM while any program runs.  They
# register here as a named line item so every preflight charges them and
# HbmBudgetError reports e.g. "kv cache blocks" next to params/opt-state.
_residents = {}
#: host-RAM residents (the KV cache's spill ring is the canonical one):
#: named line items for triage that are NOT charged against the device
#: HBM preflight — host memory is not HBM
_host_residents = {}
_residents_lock = threading.Lock()


def register_resident(name, nbytes, buffer_ids=None, host=False):
    """Charge a long-lived device allocation against every future
    preflight.  ``buffer_ids`` is an optional zero-arg callable returning
    the current ``id()`` set of the backing jax arrays — when a program's
    own arguments include those buffers (the engine's decode step takes
    the pool as donated state, already counted in argument_bytes), the
    preflight skips the double charge but keeps the named line item.
    ``host=True`` registers a host-RAM allocation instead: it appears in
    ``host_resident_items()`` (and memory triage output) but never
    counts against the device budget."""
    with _residents_lock:
        if host:
            _host_residents[name] = int(nbytes)
        else:
            _residents[name] = (int(nbytes), buffer_ids)
    obs.instant("memory.resident", cat="memory", resident=name,
                nbytes=int(nbytes), host=bool(host))


def unregister_resident(name, host=False):
    with _residents_lock:
        if host:
            return _host_residents.pop(name, None) is not None
        return _residents.pop(name, None) is not None


def resident_items():
    """Snapshot [(name, nbytes, buffer_ids_fn)] of registered residents."""
    with _residents_lock:
        return [(n, b, f) for n, (b, f) in _residents.items()]


def host_resident_items():
    """Snapshot [(name, nbytes)] of registered HOST-RAM residents."""
    with _residents_lock:
        return list(_host_residents.items())


# -- estimates ----------------------------------------------------------
def record_estimate(estimate):
    """Remember the latest per-thread estimate (bench/reporting reads it
    back via last_estimate())."""
    _state.last = estimate
    return estimate


def last_estimate():
    return getattr(_state, "last", None)


def preflight_check(compiled, program="<program>", named_buffers=None,
                    budget=None, raise_on_over=True, pipeline_depth=1,
                    per_step_io_bytes=0, resident_skip_ids=None):
    """Estimate ``compiled``'s footprint and hold it to the HBM budget.

    Runs right after AOT compilation, before the first dispatch.  Returns
    the MemoryEstimate (None when the backend has no memory analysis or
    the guard is off).  Raises HbmBudgetError when over budget, unless
    ``raise_on_over=False`` (the ladder probes budgets that way).

    ``pipeline_depth`` > 1 (PADDLE_TPU_PIPELINE_DEPTH) charges the async
    step pipeline's in-flight buffers: each of the depth-1 extra
    un-synchronized steps keeps its outputs plus ``per_step_io_bytes``
    of feeds live, so the estimate covers the pipelined steady state,
    not just one isolated step.

    Registered residents (register_resident) are charged into
    ``est.resident_bytes`` and named in ``est.buffers`` — except when
    ``resident_skip_ids`` shows the resident's backing arrays are among
    this program's own arguments (already in argument_bytes).
    """
    if not guard_enabled():
        return None
    est = analyze_compiled(compiled, program=program,
                           named_buffers=named_buffers)
    if est is None:
        return None
    extra_steps = max(0, int(pipeline_depth) - 1)
    if extra_steps:
        est.pipeline_depth = int(pipeline_depth)
        est.pipeline_bytes = extra_steps * (
            est.output_bytes + int(per_step_io_bytes))
    skip = set(resident_skip_ids or ())
    for rname, rbytes, ids_fn in resident_items():
        est.buffers.append((rname, rbytes))
        try:
            rids = set(ids_fn() or ()) if ids_fn is not None else set()
        except Exception:
            rids = set()
        if not (skip and rids & skip):
            est.resident_bytes += rbytes
    record_estimate(est)
    if budget is None:
        budget = device_hbm_budget()
    obs.instant("memory.preflight", cat="memory", program=program,
                total_bytes=est.total_bytes, temp_bytes=est.temp_bytes,
                argument_bytes=est.argument_bytes,
                pipeline_bytes=est.pipeline_bytes, budget=budget)
    if raise_on_over:
        check_budget(est, budget=budget, site=OOM_SITE)
    return est


def is_oom_error(exc):
    """Does ``exc`` look like a device allocator failure?  Matches XLA's
    RESOURCE_EXHAUSTED status and the common out-of-memory phrasings
    (and therefore also the injected ``oom`` fault)."""
    if isinstance(exc, (HbmBudgetError, TpuOutOfMemoryError)):
        return False  # already structured; don't double-wrap
    msg = str(exc)
    return ("RESOURCE_EXHAUSTED" in msg
            or "Out of memory" in msg
            or "out of memory" in msg
            or "Resource exhausted" in msg)


@contextlib.contextmanager
def oom_context(program="<program>", estimate=None, device=None,
                site=OOM_SITE):
    """Run a device dispatch; re-raise allocator failures structured.

    The ``fault_point(site)`` probe is INSIDE the try so an injected
    ``oom`` event is caught and wrapped exactly like a real
    RESOURCE_EXHAUSTED — the ladder and the diagnosis path are testable
    on CPU.  With the guard off, errors pass through untouched.
    """
    from ..distributed.fault_tolerance.plan import fault_point
    try:
        fault_point(site)
        yield
    except Exception as e:
        if not guard_enabled() or not is_oom_error(e):
            raise
        if estimate is None:
            estimate = last_estimate()
        from ..device import memory_stats
        try:
            stats = memory_stats(device)
        except Exception:
            stats = {}
        top = estimate.top_buffers(5) if estimate is not None else ()
        obs.instant("memory.oom", cat="memory", program=program,
                    site=site, error=str(e)[:200])
        raise TpuOutOfMemoryError(
            str(e), program=program, estimate=estimate,
            budget=device_hbm_budget(device), top_buffers=top,
            stats=stats, site=site) from e
