"""HBM memory guard (pre-flight prediction, diagnosis, degradation).

Three layers, consumed by both executors:

  estimator.py  pre-flight footprint from ``Compiled.memory_analysis()``
                + named parameter/optimizer-state residency, held to a
                per-device budget (``PADDLE_TPU_HBM_BUDGET`` on CPU,
                the allocator's real bytes_limit on TPU)
  guard.py      the policy plane — HbmBudgetError BEFORE dispatch,
                RESOURCE_EXHAUSTED re-raised as TpuOutOfMemoryError
                with the estimator's breakdown + live memory_stats(),
                the injectable ``exec.oom`` fault site, and the global
                remat hook
  ladder.py     opt-in degradation: remat → micro-batch grad
                accumulation → halve batch, each rung logged

See README.md §"Memory guard" for the env knobs.
"""
from .errors import (MemoryGuardError, HbmBudgetError, TpuOutOfMemoryError,
                     format_bytes)
from .estimator import (MemoryEstimate, ENV_HBM_BUDGET, parse_bytes,
                        device_hbm_budget, analyze_compiled,
                        named_buffer_sizes, check_budget)
from .guard import (ENV_MEMORY_GUARD, guard_enabled, guard_mode,
                    GuardPolicy, set_guard_policy, get_guard_policy,
                    preflight_check, oom_context, is_oom_error,
                    remat_enabled, set_remat, remat_scope, last_estimate,
                    record_estimate, register_resident,
                    unregister_resident, resident_items,
                    host_resident_items)
from .ladder import (GradAccumulator, split_feed, batch_size_of,
                     run_with_ladder)

__all__ = [
    "MemoryGuardError", "HbmBudgetError", "TpuOutOfMemoryError",
    "format_bytes",
    "MemoryEstimate", "ENV_HBM_BUDGET", "parse_bytes", "device_hbm_budget",
    "analyze_compiled", "named_buffer_sizes", "check_budget",
    "ENV_MEMORY_GUARD", "guard_enabled", "guard_mode", "GuardPolicy",
    "set_guard_policy", "get_guard_policy", "preflight_check",
    "oom_context", "is_oom_error", "remat_enabled", "set_remat",
    "remat_scope", "last_estimate", "record_estimate",
    "register_resident", "unregister_resident", "resident_items",
    "host_resident_items",
    "GradAccumulator", "split_feed", "batch_size_of", "run_with_ladder",
]
