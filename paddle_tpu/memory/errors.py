"""Structured HBM exhaustion errors (the memory guard's vocabulary).

Two failure shapes, one report format:

  HbmBudgetError       pre-flight: the compiled executable's memory
                       analysis says the program cannot fit the budget —
                       raised BEFORE any device dispatch.
  TpuOutOfMemoryError  runtime: the chip actually returned
                       RESOURCE_EXHAUSTED; re-raised with the
                       estimator's breakdown, the live allocator
                       counters, and the fault-injection site id so the
                       failure is replayable.

Both subclass RuntimeError (existing ``except RuntimeError`` /
"memory"-matching handlers keep working) and render the same top-k
largest-buffer table, so an OOM report reads identically whether it was
predicted or suffered.
"""
from __future__ import annotations

__all__ = ["MemoryGuardError", "HbmBudgetError", "TpuOutOfMemoryError",
           "format_bytes"]

_GIB = 2.0 ** 30


def format_bytes(n):
    """Human-readable byte count (MiB under 1 GiB, else GiB)."""
    if n is None:
        return "?"
    n = float(n)
    if abs(n) < 2 ** 30:
        return f"{n / 2 ** 20:.1f} MiB"
    return f"{n / _GIB:.2f} GiB"


class MemoryGuardError(RuntimeError):
    """Base for memory-guard errors.

    Attributes
    ----------
    program : str            name of the offending executable
    estimate : MemoryEstimate | None   pre-flight breakdown (if one ran)
    budget : int | None      HBM budget in bytes the program was held to
    top_buffers : list[(name, bytes)]  largest resident buffers, desc
    site : str               fault-injection site id ("exec.oom")
    """

    def __init__(self, message, program="<program>", estimate=None,
                 budget=None, top_buffers=(), site="exec.oom"):
        super().__init__(message)
        self.program = program
        self.estimate = estimate
        self.budget = budget
        self.top_buffers = list(top_buffers)
        self.site = site


def _report_lines(program, estimate, budget, top_buffers, shortfall=None):
    lines = [f"  program: {program}"]
    if estimate is not None:
        lines.append(f"  estimated footprint: "
                     f"{format_bytes(estimate.total_bytes)}"
                     f" (args {format_bytes(estimate.argument_bytes)}"
                     f" + temps {format_bytes(estimate.temp_bytes)}"
                     f" + outputs {format_bytes(estimate.output_bytes)}"
                     f" + code {format_bytes(estimate.generated_code_bytes)}"
                     f" - aliased {format_bytes(estimate.alias_bytes)})")
        if getattr(estimate, "pipeline_bytes", 0):
            lines.append(
                f"  pipeline in-flight buffers: "
                f"{format_bytes(estimate.pipeline_bytes)} "
                f"({estimate.pipeline_depth - 1} extra step(s) at "
                f"PADDLE_TPU_PIPELINE_DEPTH={estimate.pipeline_depth}; "
                f"lower the depth to 1 to reclaim)")
    if budget is not None:
        lines.append(f"  HBM budget: {format_bytes(budget)}")
    if shortfall is not None:
        lines.append(f"  shortfall: {format_bytes(shortfall)}")
    if top_buffers:
        lines.append("  largest buffers:")
        for name, nbytes in top_buffers:
            lines.append(f"    {format_bytes(nbytes):>12}  {name}")
    return lines


_HINTS = ("hints: enable the degradation ladder "
          "(PADDLE_TPU_MEMORY_GUARD=ladder / memory.GuardPolicy), enable "
          "recompute (use_recompute / memory.remat_scope), accumulate "
          "micro-batch gradients, shrink the batch, use AMP bf16, or "
          "shard params/optimizer state over a mesh axis (stage 2/3)")


class HbmBudgetError(MemoryGuardError):
    """Predicted out-of-memory: raised after lowering, before execution.

    Carries the shortfall (estimated footprint minus budget) and the
    top-k largest buffers so the report names WHAT does not fit.
    """

    def __init__(self, program, estimate, budget, top_buffers=(),
                 site="exec.oom"):
        self.shortfall = max(0, int(estimate.total_bytes) - int(budget))
        lines = ["predicted HBM out-of-memory (pre-flight check failed "
                 "before device dispatch):"]
        lines += _report_lines(program, estimate, budget, top_buffers,
                               shortfall=self.shortfall)
        lines.append(_HINTS)
        super().__init__("\n".join(lines), program=program,
                         estimate=estimate, budget=budget,
                         top_buffers=top_buffers, site=site)


class TpuOutOfMemoryError(MemoryGuardError):
    """The chip reported RESOURCE_EXHAUSTED at runtime.

    Wraps the raw XLA error with the pre-flight estimate (when one was
    computed for this executable), a live ``memory_stats()`` snapshot,
    and the fault-plan site id so the same OOM can be injected and
    replayed (``FaultPlan.add("exec.oom", "oom")``).
    """

    def __init__(self, cause_message, program="<program>", estimate=None,
                 budget=None, top_buffers=(), stats=None, site="exec.oom"):
        self.stats = dict(stats or {})
        lines = [f"out of device memory in {program!r} "
                 f"(RESOURCE_EXHAUSTED at site {site!r}):",
                 f"  {cause_message.strip().splitlines()[0][:300]}"]
        lines += _report_lines(program, estimate, budget, top_buffers)
        if self.stats:
            lines.append("  live allocator:")
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                        "largest_alloc_size"):
                if key in self.stats:
                    lines.append(
                        f"    {key:<22}{format_bytes(self.stats[key])}")
        lines.append(_HINTS)
        super().__init__("\n".join(lines), program=program,
                         estimate=estimate, budget=budget,
                         top_buffers=top_buffers, site=site)
