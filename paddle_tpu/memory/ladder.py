"""The degradation ladder: retry an OOMing train step, rung by rung.

Rung order (GuardPolicy.rungs):

  remat        flip the global recompute hook on — transformer/GPT
               blocks re-trace under jax.checkpoint, trading FLOPs for
               activation memory
  grad_accum   split the batch into ``policy.micro_batches``
               micro-batches and accumulate gradients through the
               optimizer's pre-step hook chain (apply every k-th step,
               grads scaled by 1/k so the applied update equals the
               full-batch step)
  halve_batch  last resort: halve the batch with a loud warning,
               repeatedly, down to ``policy.min_batch``

``run_with_ladder`` drives an eager/jit train step through the rungs on
*predicted* OOM (HbmBudgetError escaping a guarded jit compile) or
*actual* OOM (TpuOutOfMemoryError / RESOURCE_EXHAUSTED, including the
injected ``exec.oom`` fault).  Every rung taken is recorded on the
policy and logged at WARNING so degraded runs are visibly degraded.
"""
from __future__ import annotations

import logging

import numpy as np

from .errors import MemoryGuardError
from .guard import (GuardPolicy, get_guard_policy, is_oom_error,
                    remat_enabled, set_remat)

__all__ = ["GradAccumulator", "split_feed", "batch_size_of",
           "run_with_ladder"]

logger = logging.getLogger("paddle_tpu.memory")


# -- gradient accumulation via the optimizer pre-step hook ---------------
class GradAccumulator:
    """Accumulate gradients over ``k`` optimizer.step() calls.

    Rides the PR-1 pre-step hook chain: on non-boundary steps the hook
    sets ``optimizer._skip_apply`` so step() keeps the accumulated
    ``p.grad`` and does not advance the step counter; on every k-th call
    it scales the summed grads by 1/k (micro-losses are means over B/k,
    so the applied update equals the full-batch mean-loss step) and
    lets the fused apply run.
    """

    def __init__(self, k):
        if int(k) < 1:
            raise ValueError(f"GradAccumulator: k must be >= 1, got {k}")
        self.k = int(k)
        self._count = 0
        self._opt = None
        self._remove = None
        self.just_applied = False

    def attach(self, optimizer):
        """Bind to ``optimizer`` and register on the global pre-step
        hook chain.  Returns a zero-arg remover (also ``detach``)."""
        from ..optimizer.optimizer import register_pre_step_hook
        self._opt = optimizer
        self._count = 0
        self._remove = register_pre_step_hook(self)
        return self.detach

    def detach(self):
        if self._remove is not None:
            self._remove()
            self._remove = None
        self._opt = None

    def __call__(self, optimizer, params):
        if self._opt is not None and optimizer is not self._opt:
            return  # a different optimizer's step; not ours to gate
        self._count += 1
        if self._count % self.k != 0:
            self.just_applied = False
            optimizer._skip_apply = True
            return
        inv = 1.0 / self.k
        for p in params:
            if p.grad is not None:
                p.grad._local_value_update(p.grad._value * inv)
        self.just_applied = True


# -- feed slicing --------------------------------------------------------
def batch_size_of(feed, axis=0):
    """Leading-dim size shared by the batched arrays in ``feed``
    (None when nothing in the feed has a batch axis)."""
    for v in feed.values():
        a = np.asarray(getattr(v, "_value", v))
        if a.ndim > axis:
            return int(a.shape[axis])
    return None


def split_feed(feed, k, axis=0):
    """Split ``feed``'s batch axis into ``k`` contiguous micro-feeds.

    Only arrays whose leading dim equals the feed's batch size are
    sliced; scalars and non-batched values ride along whole.  ``k`` is
    clamped to the batch size; micro-batches must divide evenly (the
    1/k grad scaling assumes equal sizes) — trailing remainder rows go
    to the last micro-batch only when unavoidable, with a warning.
    """
    b = batch_size_of(feed, axis)
    if b is None or b <= 1:
        return [feed]
    k = max(1, min(int(k), b))
    if b % k:
        logger.warning("split_feed: batch %d not divisible by %d "
                       "micro-batches; grad-accum equivalence is "
                       "approximate", b, k)
    bounds = [round(i * b / k) for i in range(k + 1)]
    micros = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        m = {}
        for name, v in feed.items():
            a = np.asarray(getattr(v, "_value", v))
            if a.ndim > axis and a.shape[axis] == b:
                idx = [slice(None)] * a.ndim
                idx[axis] = slice(lo, hi)
                m[name] = a[tuple(idx)]
            else:
                m[name] = v
        micros.append(m)
    return micros


def _halve_feed(feed, axis=0):
    b = batch_size_of(feed, axis)
    half = max(1, b // 2)
    out = {}
    for name, v in feed.items():
        a = np.asarray(getattr(v, "_value", v))
        if a.ndim > axis and a.shape[axis] == b:
            idx = [slice(None)] * a.ndim
            idx[axis] = slice(0, half)
            out[name] = a[tuple(idx)]
        else:
            out[name] = v
    return out, half


# -- the ladder ----------------------------------------------------------
def _oomish(exc):
    return isinstance(exc, MemoryGuardError) or is_oom_error(exc)


def run_with_ladder(forward_backward, feed, optimizer=None, policy=None,
                    batch_axis=0):
    """Run one train step, degrading through the ladder on OOM.

    ``forward_backward(feed)`` computes the loss and runs backward
    (populating ``p.grad``); ``optimizer.step()`` / ``clear_grad()``
    are driven here so the grad-accum rung can gate them.  With
    ``optimizer=None`` only inference-style retries apply (remat,
    halve_batch).

    Returns ``(loss, policy)`` — ``policy.taken`` lists the rungs
    engaged, ``[]`` for a clean first-try run.
    """
    policy = (policy if policy is not None
              else get_guard_policy() or GuardPolicy())
    pending = [r for r in policy.rungs]
    cur_feed = feed
    accum = False

    def _attempt():
        if accum and optimizer is not None:
            micros = split_feed(cur_feed, policy.micro_batches, batch_axis)
            acc = GradAccumulator(len(micros))
            acc.attach(optimizer)
            try:
                for m in micros:
                    loss = forward_backward(m)
                    optimizer.step()
            finally:
                acc.detach()
            optimizer.clear_grad()
            return loss
        loss = forward_backward(cur_feed)
        if optimizer is not None:
            optimizer.step()
            optimizer.clear_grad()
        return loss

    while True:
        try:
            return _attempt(), policy
        except Exception as e:
            if not _oomish(e):
                raise
            if optimizer is not None:
                optimizer.clear_grad()  # drop partial accumulation
            engaged = False
            while pending and not engaged:
                rung = pending.pop(0)
                if rung == "remat":
                    if remat_enabled():
                        continue
                    set_remat(True)
                    policy.record("remat",
                                  "recompute enabled on guarded blocks")
                    engaged = True
                elif rung == "grad_accum":
                    if optimizer is None or accum:
                        continue
                    b = batch_size_of(cur_feed, batch_axis)
                    if b is None or b <= 1:
                        continue
                    accum = True
                    policy.record(
                        "grad_accum",
                        f"{min(policy.micro_batches, b)} micro-batches "
                        f"over batch {b}")
                    engaged = True
                elif rung == "halve_batch":
                    b = batch_size_of(cur_feed, batch_axis)
                    if b is None or b <= policy.min_batch:
                        continue
                    cur_feed, half = _halve_feed(cur_feed, batch_axis)
                    policy.record("halve_batch",
                                  f"batch {b} -> {half}")
                    logger.warning(
                        "memory guard: HALVING BATCH %d -> %d — results "
                        "are NOT comparable to the requested batch size",
                        b, half)
                    if half > policy.min_batch:
                        pending.insert(0, "halve_batch")  # may halve again
                    engaged = True
            if not engaged:
                logger.error("memory guard: degradation ladder exhausted "
                             "(rungs taken: %s); re-raising", policy.taken)
                raise
