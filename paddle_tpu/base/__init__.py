"""paddle.base compat (the old paddle.fluid surface).

Reference parity: `python/paddle/base/` [UNVERIFIED — empty reference
mount].  Exposes the handles legacy scripts touch: core, framework,
executor, program guards, dygraph guards.
"""
from __future__ import annotations

from ..static.framework import (Program, program_guard,
                                default_main_program,
                                default_startup_program, in_dygraph_mode,
                                global_scope, name_scope)
from ..static.executor import Executor
from ..core.place import CPUPlace, CUDAPlace, TPUPlace, CUDAPinnedPlace
from ..core.tensor import Tensor


class _CoreShim:
    """paddle.base.core stand-in (the pybind module in the reference)."""

    from ..core.place import CPUPlace, CUDAPlace, TPUPlace  # noqa

    class VarDesc:
        class VarType:
            FP32 = "float32"
            FP64 = "float64"
            FP16 = "float16"
            BF16 = "bfloat16"
            INT32 = "int32"
            INT64 = "int64"
            BOOL = "bool"
            UINT8 = "uint8"
            INT8 = "int8"

    @staticmethod
    def is_compiled_with_cuda():
        return False

    @staticmethod
    def is_compiled_with_xpu():
        return False


core = _CoreShim()


class dygraph:
    @staticmethod
    def guard(place=None):
        import contextlib

        @contextlib.contextmanager
        def g():
            from ..static.framework import disable_static, in_static_mode, \
                enable_static
            was_static = in_static_mode()
            disable_static()
            try:
                yield
            finally:
                if was_static:
                    enable_static()

        return g()

    @staticmethod
    def to_variable(value, name=None, zero_copy=None):
        from ..core.tensor import to_tensor
        return to_tensor(value)


def executor_global_scope():
    return global_scope()
