"""paddle.distribution: probability distributions.

Reference parity: `python/paddle/distribution/` (Distribution base,
Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/..., kl_divergence,
register_kl [UNVERIFIED — empty reference mount]).

TPU-native: sampling uses the framework's seeded generator
(paddle.seed → jax.random key folding), densities are jnp expressions
routed through dispatch so log_prob/entropy are differentiable on the
tape and traceable under to_static.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "Bernoulli", "Beta", "Dirichlet", "Exponential", "Gamma",
           "Geometric", "Gumbel", "Laplace", "LogNormal", "Multinomial",
           "Poisson", "kl_divergence", "register_kl"]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if isinstance(
        x, (int, float, list, tuple)) else jnp.asarray(x)



def _keep(x):
    """Preserve the caller's Tensor (so log_prob gradients reach it);
    wrap raw values."""
    if isinstance(x, Tensor):
        return x
    return _wrap(jnp.asarray(x, jnp.float32) if isinstance(
        x, (int, float, list, tuple)) else jnp.asarray(x))

def _next_key():
    from ..framework import random as prandom
    return prandom.default_generator().next_key()


def _wrap(v):
    return Tensor(v, _internal=True, stop_gradient=True)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp
        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_t, self.scale_t = _keep(loc), _keep(scale)
        self.loc = self.loc_t._value
        self.scale = self.scale_t._value
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(
            self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(
            jnp.square(self.scale), self.batch_shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        z = jax.random.normal(_next_key(), shape, jnp.float32)
        return _wrap(self.loc + self.scale * z)

    rsample = sample

    def log_prob(self, value):
        def impl(v, loc, scale):
            var = jnp.square(scale)
            return (-jnp.square(v - loc) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return dispatch("normal_log_prob", impl,
                        (value, self.loc_t, self.scale_t))

    def entropy(self):
        return _wrap(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def cdf(self, value):
        return dispatch(
            "normal_cdf",
            lambda v, loc, scale: 0.5 * (1 + jax.lax.erf(
                (v - loc) / (scale * math.sqrt(2)))),
            (value, self.loc_t, self.scale_t))


class LogNormal(Normal):
    def sample(self, shape=()):
        return _wrap(jnp.exp(super().sample(shape)._value))

    rsample = sample

    # the inherited Normal statistics describe ln X, not X — override
    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(
            jnp.exp(self.loc + jnp.square(self.scale) / 2),
            self.batch_shape))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return _wrap(jnp.broadcast_to(
            (jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2),
            self.batch_shape))

    def entropy(self):
        return _wrap(jnp.broadcast_to(
            self.loc + 0.5 + 0.5 * math.log(2 * math.pi)
            + jnp.log(self.scale), self.batch_shape))

    def cdf(self, value):
        return dispatch(
            "lognormal_cdf",
            lambda v, loc, scale: 0.5 * (1 + jax.lax.erf(
                (jnp.log(v) - loc) / (scale * math.sqrt(2)))),
            (value, self.loc_t, self.scale_t))

    def log_prob(self, value):
        def impl(v, loc, scale):
            lv = jnp.log(v)
            var = jnp.square(scale)
            return (-jnp.square(lv - loc) / (2 * var) - lv
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return dispatch("lognormal_log_prob", impl,
                        (value, self.loc_t, self.scale_t))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape, jnp.float32)
        return _wrap(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        def impl(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return dispatch("uniform_log_prob", impl,
                        (value, _wrap(self.low), _wrap(self.high)),
                        differentiable=False)

    def entropy(self):
        return _wrap(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("one of logits/probs is required")
        if logits is not None and probs is None:
            self.logits = _val(logits)
        else:
            self.logits = jnp.log(jnp.clip(_val(probs), 1e-38))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return _wrap(jax.nn.softmax(self.logits, -1))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.categorical(_next_key(), self.logits,
                                     shape=shape)
        return _wrap(out)

    def log_prob(self, value):
        def impl(v, logits):
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], -1)[..., 0]
        return dispatch("categorical_log_prob", impl,
                        (value, _wrap(self.logits)))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return _wrap(-jnp.sum(jnp.exp(logp) * logp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = jnp.clip(_val(probs), 1e-7, 1 - 1e-7)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape)
        return _wrap((u < self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        def impl(v, p):
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return dispatch("bernoulli_log_prob", impl,
                        (value, _wrap(self.probs_)))

    def entropy(self):
        p = self.probs_
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(jax.random.beta(_next_key(), self.alpha, self.beta,
                                     shape))

    def log_prob(self, value):
        def impl(v, a, b):
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return dispatch("beta_log_prob", impl,
                        (value, _wrap(self.alpha), _wrap(self.beta)))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(jax.random.dirichlet(_next_key(),
                                          self.concentration, shape))

    def log_prob(self, value):
        def impl(v, c):
            lognorm = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                       - jax.scipy.special.gammaln(jnp.sum(c, -1)))
            return jnp.sum((c - 1) * jnp.log(v), -1) - lognorm
        return dispatch("dirichlet_log_prob", impl,
                        (value, _wrap(self.concentration)))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(jax.random.exponential(_next_key(), shape)
                     / self.rate)

    def log_prob(self, value):
        return dispatch(
            "exponential_log_prob",
            lambda v, r: jnp.log(r) - r * v, (value, _wrap(self.rate)))

    def entropy(self):
        return _wrap(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(jax.random.gamma(_next_key(), self.concentration,
                                      shape) / self.rate)

    def log_prob(self, value):
        def impl(v, a, r):
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(a))
        return dispatch("gamma_log_prob", impl,
                        (value, _wrap(self.concentration),
                         _wrap(self.rate)))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = jnp.clip(_val(probs), 1e-7, 1 - 1e-7)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape)
        return _wrap(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        return dispatch(
            "geometric_log_prob",
            lambda v, p: v * jnp.log1p(-p) + jnp.log(p),
            (value, _wrap(self.probs_)))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_t, self.scale_t = _keep(loc), _keep(scale)
        self.loc = self.loc_t._value
        self.scale = self.scale_t._value
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        g = jax.random.gumbel(_next_key(), shape)
        return _wrap(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        def impl(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return dispatch("gumbel_log_prob", impl,
                        (value, self.loc_t, self.scale_t))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_t, self.scale_t = _keep(loc), _keep(scale)
        self.loc = self.loc_t._value
        self.scale = self.scale_t._value
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(self.loc + self.scale
                     * jax.random.laplace(_next_key(), shape))

    rsample = sample

    def log_prob(self, value):
        def impl(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return dispatch("laplace_log_prob", impl,
                        (value, self.loc_t, self.scale_t))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=()):
        logits = jnp.log(jnp.clip(self.probs_, 1e-38))
        draws = jax.random.categorical(
            _next_key(), logits,
            shape=tuple(shape) + (self.total_count,) + self.batch_shape)
        k = self.probs_.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return _wrap(jnp.sum(onehot, axis=len(tuple(shape))))

    def log_prob(self, value):
        def impl(v, p):
            logc = (jax.scipy.special.gammaln(
                jnp.sum(v, -1) + 1)
                - jnp.sum(jax.scipy.special.gammaln(v + 1), -1))
            return logc + jnp.sum(v * jnp.log(jnp.clip(p, 1e-38)), -1)
        return dispatch("multinomial_log_prob", impl,
                        (value, _wrap(self.probs_)))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _wrap(jax.random.poisson(_next_key(), self.rate,
                                        shape).astype(jnp.float32))

    def log_prob(self, value):
        def impl(v, r):
            return (v * jnp.log(r) - r
                    - jax.scipy.special.gammaln(v + 1))
        return dispatch("poisson_log_prob", impl,
                        (value, _wrap(self.rate)))


# ---- KL registry ---------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    # most-specific registration wins (a subclass pair beats its base
    # pair regardless of registration order), like the reference
    best, best_depth = None, -1
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            depth = (len(type(p).__mro__) - type(p).__mro__.index(pc)) \
                + (len(type(q).__mro__) - type(q).__mro__.index(qc))
            if depth > best_depth:
                best, best_depth = fn, depth
    if best is not None:
        return best(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return _wrap(jnp.sum(jnp.exp(logp) * (logp - logq), -1))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return _wrap(jnp.where(inside, kl, jnp.inf))
