"""Pallas/Mosaic tiling legality: the TPU1xx analyzer.

Mosaic lays the last two dims of every array crossing a ``pallas_call``
boundary onto (sublane, lane) vector registers.  The minimum legal tile
depends on itemsize — (8,128) for 4-byte dtypes, (16,128) for 2-byte,
(32,128) for 1-byte — and a block dim must either equal the array dim
or be a multiple of the minimum tile, with the grid covering the array
exactly.  Violating either is a Mosaic *compile* error on hardware
(the (1,128) flash-attention block that killed BENCH_r02), which the
interpret-mode CPU path never sees; this module checks the same rules
statically so the CLI and the gate catch them before dispatch.

Checks are pure shape arithmetic — no jax import, no tracing — so the
gate can diagnose a failed probe without paying a second compile.
"""
from __future__ import annotations

import math

import numpy as np

from .diagnostics import Diagnostic, DiagnosticReport

__all__ = ["LANE", "VMEM_BYTES", "min_tile", "check_block_spec",
           "check_pallas_call", "estimate_vmem_bytes",
           "audit_flash_attention", "audit_paged_attention",
           "audit_ragged_attention", "audit_layer_norm_residual",
           "audit_matmul_epilogue", "audit_grouped_matmul",
           "audit_lora_sgmv"]

LANE = 128
# per-core VMEM; Mosaic needs headroom for double buffering, so the
# estimate errors at the full budget and stays silent below it.
VMEM_BYTES = 16 * 1024 * 1024

# itemsize (bytes) -> minimum sublane rows. 8-byte dtypes only appear
# when x64 leaks into a kernel; treat them like 4-byte for the sublane
# rule (the dtype itself is flagged by the TPU4xx audit).
_MIN_SUBLANE = {8: 8, 4: 8, 2: 16, 1: 32}


def min_tile(dtype):
    """Minimum legal (sublane, lane) tile for ``dtype``."""
    itemsize = np.dtype(dtype).itemsize
    return _MIN_SUBLANE.get(itemsize, 8), LANE


def _fmt(shape):
    return "(" + ",".join(str(s) for s in shape) + ")"


def check_block_spec(block_shape, array_shape, dtype, *, site="",
                     operand=""):
    """Diagnostics for one operand's BlockSpec against Mosaic rules.

    ``block_shape`` of None means "whole array" (always legal).  Rules
    checked on the last two dims: minimum sublane/lane tile (TPU101),
    grid coverage / divisibility (TPU102), rank (TPU104).
    """
    where = f"{site}[{operand}]" if operand else site
    diags = []
    if block_shape is None:
        return diags
    block_shape = tuple(int(b) for b in block_shape)
    array_shape = tuple(int(a) for a in array_shape)
    if len(block_shape) != len(array_shape):
        diags.append(Diagnostic(
            "TPU102",
            f"block rank {len(block_shape)} != array rank "
            f"{len(array_shape)} ({_fmt(block_shape)} vs "
            f"{_fmt(array_shape)})",
            site=where))
        return diags
    if len(array_shape) < 2:
        diags.append(Diagnostic(
            "TPU104",
            f"rank-{len(array_shape)} array {_fmt(array_shape)} crosses "
            "the kernel boundary; Mosaic tiles the last two dims",
            site=where,
            hint="reshape to at least 2D (e.g. (1, n)) before the "
                 "pallas_call"))
        return diags

    sub_min, lane_min = min_tile(dtype)
    dname = np.dtype(dtype).name
    # leading (grid-mapped) dims only need to divide the array dims
    for i, (b, a) in enumerate(zip(block_shape[:-2], array_shape[:-2])):
        if b <= 0 or a % b:
            diags.append(Diagnostic(
                "TPU102",
                f"leading block dim {i} = {b} does not divide array "
                f"dim {a}",
                site=where,
                hint="pad the array or pick a divisor block"))
    for name, lim, b, a in (
            ("sublane", sub_min, block_shape[-2], array_shape[-2]),
            ("lane", lane_min, block_shape[-1], array_shape[-1])):
        if b <= 0:
            diags.append(Diagnostic(
                "TPU102", f"non-positive {name} block dim {b}",
                site=where))
            continue
        full = b == a
        if not full and b % lim:
            diags.append(Diagnostic(
                "TPU101",
                f"{name} block dim {b} of {_fmt(block_shape)} is not a "
                f"multiple of the {dname} minimum {lim} "
                f"(min tile ({sub_min},{lane_min}))",
                site=where,
                hint=f"round the {name} dim up to a multiple of {lim} "
                     "or pass the full array dim"))
        elif not full and a % b:
            diags.append(Diagnostic(
                "TPU102",
                f"{name} block dim {b} does not divide array dim {a}; "
                "the grid leaves a ragged tail",
                site=where,
                hint="pad the array to a block multiple before the "
                     "kernel (the repo's kernels pad with _round_up)"))
    return diags


def estimate_vmem_bytes(operands, scratch=()):
    """Rough per-grid-step VMEM working set: one block per operand
    (double-buffered) plus scratch buffers."""
    total = 0
    for block_shape, array_shape, dtype in operands:
        shape = array_shape if block_shape is None else block_shape
        total += 2 * int(math.prod(int(s) for s in shape)) * \
            np.dtype(dtype).itemsize
    for shape, dtype in scratch:
        total += int(math.prod(int(s) for s in shape)) * \
            np.dtype(dtype).itemsize
    return total


def check_pallas_call(operands, *, scratch=(), site="pallas_call",
                      vmem_budget=VMEM_BYTES):
    """Validate a whole kernel's block plan.

    ``operands``: iterable of (name, block_shape_or_None, array_shape,
    dtype).  ``scratch``: iterable of (shape, dtype) resident per grid
    step.  Returns a ``DiagnosticReport`` of TPU101/102/103/104.
    """
    report = DiagnosticReport(label=site)
    sized = []
    for name, block_shape, array_shape, dtype in operands:
        report.extend(check_block_spec(block_shape, array_shape, dtype,
                                       site=site, operand=name))
        sized.append((block_shape, array_shape, dtype))
    vmem = estimate_vmem_bytes(sized, scratch)
    if vmem > vmem_budget:
        report.add(Diagnostic(
            "TPU103",
            f"estimated VMEM working set {vmem / 2**20:.1f} MiB exceeds "
            f"the {vmem_budget / 2**20:.0f} MiB budget",
            site=site,
            hint="shrink block dims or stage fewer operands per grid "
                 "step",
            data={"vmem_bytes": vmem}))
    return report


def audit_flash_attention(batch, seq_q, seq_k, heads, head_dim,
                          dtype="float32", causal=False,
                          direction="fwd"):
    """Statically validate the exact block plan the flash kernels would
    use for these shapes (see ``ops.pallas_kernels.flash_block_plan``).
    ``direction``: ``"fwd"``, ``"bwd_dq"`` or ``"bwd_dkv"``."""
    from ..ops.pallas_kernels import flash_block_plan
    plan = flash_block_plan(batch, seq_q, seq_k, heads, head_dim,
                            dtype=dtype, direction=direction)
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()),
        site=f"flash_attention.{direction}[{np.dtype(dtype).name} "
             f"q={seq_q} k={seq_k} d={head_dim}]")
    report.plan = plan
    return report


def audit_layer_norm_residual(rows, hidden, dtype="float32",
                              direction="fwd"):
    """Statically validate the fused layernorm+residual block plan
    (see ``ops.pallas_fused.ln_residual_block_plan``)."""
    from ..ops.pallas_fused import ln_residual_block_plan
    plan = ln_residual_block_plan(rows, hidden, dtype=dtype,
                                  direction=direction)
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()),
        site=f"layer_norm_residual.{direction}"
             f"[{np.dtype(dtype).name} rows={rows} n={hidden}]")
    report.plan = plan
    return report


def audit_matmul_epilogue(m, k, n, dtype="float32", direction="fwd",
                          weight_dtype=None):
    """Statically validate the matmul-epilogue fusion block plan
    (see ``ops.pallas_fused.matmul_epilogue_block_plan``).

    ``weight_dtype="int8"`` audits the dequant-fused int8-weight
    variant; tile violations on the int8 operand additionally raise
    TPU405 (int8 needs (32,128)-legal tiles)."""
    from ..ops.pallas_fused import matmul_epilogue_block_plan
    plan = matmul_epilogue_block_plan(m, k, n, dtype=dtype,
                                      direction=direction,
                                      weight_dtype=weight_dtype)
    wtag = ""
    if weight_dtype is not None:
        wtag = f" w={np.dtype(weight_dtype).name}"
    site = (f"matmul_epilogue.{direction}"
            f"[{np.dtype(dtype).name}{wtag} m={m} k={k} n={n}]")
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()), site=site)
    _flag_int8_relayout(report, plan, site=site)
    report.plan = plan
    return report


def audit_grouped_matmul(tokens, k, n, num_experts, dtype="float32",
                         direction="fwd"):
    """Statically validate the grouped-expert matmul block plan
    (see ``ops.pallas_grouped.grouped_matmul_block_plan``).

    The scalar-prefetched ``block_group`` descriptor is untiled and
    omitted from the plan, like the ragged kernels' block tables."""
    from ..ops.pallas_grouped import grouped_matmul_block_plan
    plan = grouped_matmul_block_plan(tokens, k, n, num_experts,
                                     dtype=dtype, direction=direction)
    site = (f"grouped_matmul.{direction}"
            f"[{np.dtype(dtype).name} tokens={tokens} k={k} n={n} "
            f"e={num_experts}]")
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()), site=site)
    report.plan = plan
    return report


def audit_lora_sgmv(tokens, k, n, rank, num_adapters, dtype="float32",
                    direction="fwd", block_rows=None):
    """Statically validate the segmented LoRA SGMV epilogue block plan
    (see ``ops.pallas_grouped.lora_epilogue_block_plan``).

    The scalar-prefetched ``block_adapter`` descriptor is untiled and
    omitted from the plan, like the grouped kernel's ``block_group``."""
    from ..ops.pallas_grouped import lora_epilogue_block_plan
    plan = lora_epilogue_block_plan(tokens, k, n, rank, num_adapters,
                                    dtype=dtype, direction=direction,
                                    block_rows=block_rows)
    site = (f"lora_sgmv.{direction}"
            f"[{np.dtype(dtype).name} tokens={tokens} k={k} n={n} "
            f"r={rank} adapters={num_adapters}]")
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()), site=site)
    report.plan = plan
    return report


def _flag_int8_relayout(report, plan, *, site):
    """Append TPU405 when an int8 operand in ``plan`` has a tile
    violation (TPU101/TPU102): int8 demands (32,128)-legal tiles, and
    an illegal block forces Mosaic to relayout the narrow operand."""
    int8_ops = {name for name, block, shape, dtype in plan["operands"]
                if np.dtype(dtype).itemsize == 1}
    if not int8_ops:
        return
    hit = any(d.code in ("TPU101", "TPU102") and
              any(f"[{op}]" in (d.site or "") for op in int8_ops)
              for d in report)
    if hit:
        report.add(Diagnostic(
            "TPU405",
            "int8 operand tiled below the (32,128) minimum: Mosaic "
            "relayouts the quantized tensor before the MXU",
            site=site,
            hint="round the sublane block dim up to 32 (int8 itemsize "
                 "1 => 32-row minimum tile)"))


def audit_paged_attention(num_heads, head_dim, block_size, num_blocks=64,
                          dtype="float32"):
    """Statically validate the paged decode-attention block plan."""
    from ..ops.pallas_kernels import paged_block_plan
    plan = paged_block_plan(num_heads, head_dim, block_size,
                            num_blocks=num_blocks, dtype=dtype)
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()),
        site=f"paged_attention[{np.dtype(dtype).name} H={num_heads} "
             f"D={head_dim} bs={block_size}]")
    report.plan = plan
    return report


def audit_ragged_attention(num_heads, head_dim, block_size,
                           num_q_blocks=4, block_q=None, num_blocks=64,
                           table_width=8, dtype="float32",
                           kv_dtype=None):
    """Statically validate the ragged mixed prefill+decode attention
    block plan (see ``ops.pallas_ragged.ragged_block_plan``).

    ``kv_dtype="int8"`` audits the quantized-KV variant, whose plan
    carries int8 k/v pools plus f32 per-slot scale tables; int8 tile
    violations additionally raise TPU405."""
    from ..ops.pallas_ragged import ragged_block_plan
    plan = ragged_block_plan(num_heads, head_dim, block_size,
                             num_q_blocks=num_q_blocks, block_q=block_q,
                             num_blocks=num_blocks,
                             table_width=table_width, dtype=dtype,
                             kv_dtype=kv_dtype)
    kvtag = ""
    if kv_dtype is not None:
        kvtag = f" kv={np.dtype(kv_dtype).name}"
    site = (f"ragged_attention[{np.dtype(dtype).name}{kvtag} "
            f"H={num_heads} D={head_dim} bs={block_size} "
            f"bq={plan['block_q']}]")
    report = check_pallas_call(
        plan["operands"], scratch=plan.get("scratch", ()), site=site)
    _flag_int8_relayout(report, plan, site=site)
    report.plan = plan
    return report
