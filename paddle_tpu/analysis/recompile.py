"""Recompile-risk analyzer: the TPU2xx family.

Everything on TPU compiles; the question is how often.  Three caches
hold the evidence, and this module audits their key structure instead
of adding instrumentation:

* ``static.executor.Executor._shared_cache`` — keyed
  ``(id(program), fingerprint, feed_sig, fetch_sig)``.  Same program +
  fingerprint with many distinct feed signatures = shape drift
  (TPU202); same program id with several fingerprints = in-place
  structural mutation (TPU204).
* ``jit.trace.TracedFunction._cache`` — keyed by ``_tree_key`` strings
  whose leaf tokens are ``T{shape}:{dtype}`` (Tensors),
  ``A{shape}:{dtype}`` (arrays) and ``V{value!r}`` (static python
  leaves).  Two keys over the same treedef differing only in a ``V``
  token = a python scalar baked into the trace (TPU203); differing in a
  ``T``/``A`` shape = shape drift (TPU202).
* ``core.dispatch._eager_fwd_cache`` — per-op executables keyed
  ``(name, code, statics, attr_sig, aval_sig)``.  One op accumulating
  many entries that differ only in statics/avals is the
  per-op-recompile signature of the 1000x-off eager path.

Weak-typed inputs (TPU201) are read straight off a traced jaxpr's
invars.

The lazy auto-trace tier adds a fourth cache: ``core.lazy``'s
fingerprinted segment executables.  A healthy training loop replays ONE
fingerprint forever; an op sequence that keeps compiling new
fingerprints (TPU205) is paying a whole-segment XLA compile per step —
the audit diffs the per-node structural keys of the colliding variants
to NAME the node that keeps changing (a baked-in python scalar, a
drifting input shape).
"""
from __future__ import annotations

import os
from collections import defaultdict

from .diagnostics import Diagnostic

__all__ = ["audit_executor_cache", "audit_trace_cache",
           "audit_eager_cache", "audit_segment_cache",
           "audit_weak_types"]

# distinct variants of "the same" program/call tolerated before the
# churn diagnostics fire (2 shapes may be train vs eval; 3+ is drift)
DRIFT_THRESHOLD = 3


def audit_executor_cache(cache=None, threshold=DRIFT_THRESHOLD):
    """TPU202/TPU204 over the executor's shared executable cache."""
    if cache is None:
        from ..static.executor import Executor
        cache = Executor._shared_cache
    diags = []
    by_prog = defaultdict(set)        # (pid, fp, fetch) -> {feed_sig}
    fps = defaultdict(set)            # pid -> {fingerprint}
    labels = {}
    for key, entry in list(cache.items()):
        try:
            pid, fp, feed_sig, fetch_sig = key
        except (TypeError, ValueError):
            continue
        by_prog[(pid, fp, fetch_sig)].add(feed_sig)
        fps[pid].add(fp)
        if isinstance(entry, dict):
            labels[pid] = entry.get("program_label", f"program#{pid}")
    for (pid, fp, fetch_sig), feeds in by_prog.items():
        if len(feeds) >= threshold:
            shapes = sorted(str(dict(f)) for f in feeds)[:4]
            diags.append(Diagnostic(
                "TPU202",
                f"{labels.get(pid, f'program#{pid}')} compiled for "
                f"{len(feeds)} distinct feed shapes (e.g. "
                f"{'; '.join(shapes)})",
                site=labels.get(pid, f"program#{pid}"),
                hint="pad or bucket batch/sequence dims to a fixed set "
                     "of shapes; each new shape pays a full XLA compile",
                data={"variants": len(feeds)}))
    for pid, fpset in fps.items():
        if len(fpset) > 1:
            diags.append(Diagnostic(
                "TPU204",
                f"{labels.get(pid, f'program#{pid}')} was structurally "
                f"mutated in place: {len(fpset)} fingerprints cached "
                "for one Program object",
                site=labels.get(pid, f"program#{pid}"),
                hint="clone() the program before editing it, or expect "
                     "a rebuild of every cached executable"))
    return diags


def _parse_tree_key(key):
    """(treedef_str, leaf_tokens) from a _tree_key string, else None."""
    if isinstance(key, tuple):          # (tree_key, remat) cache key
        key = key[0]
    if not isinstance(key, str):
        return None
    parts = key.split("|")
    return parts[0], parts[1:]


def audit_trace_cache(traced, threshold=DRIFT_THRESHOLD):
    """TPU202/TPU203 over one TracedFunction's signature cache."""
    cache = getattr(traced, "_cache", traced)
    label = getattr(getattr(traced, "_orig_fn", None), "__qualname__",
                    None) or "to_static"
    site = f"jit:{label}"
    groups = defaultdict(list)        # treedef -> [leaf_tokens]
    for key in list(cache.keys() if hasattr(cache, "keys") else cache):
        parsed = _parse_tree_key(key)
        if parsed:
            groups[parsed[0]].append(parsed[1])
    diags = []
    for treedef, variants in groups.items():
        if len(variants) < 2:
            continue
        scalar_slots, shape_slots = set(), set()
        width = min(len(v) for v in variants)
        for pos in range(width):
            tokens = {v[pos] for v in variants}
            if len(tokens) == 1:
                continue
            if all(t.startswith("V") for t in tokens):
                scalar_slots.add(pos)
            else:
                shape_slots.add(pos)
        if scalar_slots and len(variants) >= 2:
            examples = sorted(
                {v[pos] for v in variants for pos in scalar_slots})[:5]
            diags.append(Diagnostic(
                "TPU203",
                f"{len(variants)} traces of {label} differ only by "
                f"python-scalar argument value(s) {examples}: each new "
                "value is a fresh compile",
                site=site,
                hint="pass changing scalars as 0-d tensors "
                     "(paddle.to_tensor(x)) so they ride as runtime "
                     "arguments",
                data={"variants": len(variants)}))
        if shape_slots and len(variants) >= threshold:
            diags.append(Diagnostic(
                "TPU202",
                f"{len(variants)} traces of {label} differ in tensor "
                "shape/dtype: shape drift recompiles the step",
                site=site,
                hint="pad or bucket inputs to a fixed shape set",
                data={"variants": len(variants)}))
    return diags


def audit_eager_cache(cache=None, per_op_threshold=16):
    """Flag ops accumulating many per-signature eager executables."""
    if cache is None:
        from ..core.dispatch import _eager_fwd_cache
        cache = _eager_fwd_cache
    per_op = defaultdict(lambda: {"n": 0, "statics": set(),
                                  "avals": set()})
    for key in list(cache.keys()):
        try:
            name, _code, statics, attr_sig, aval_sig = key
        except (TypeError, ValueError):
            continue
        rec = per_op[name]
        rec["n"] += 1
        rec["statics"].add((statics, attr_sig))
        rec["avals"].add(aval_sig)
    diags = []
    for name, rec in sorted(per_op.items(), key=lambda kv: -kv[1]["n"]):
        if rec["n"] < per_op_threshold:
            continue
        if len(rec["statics"]) > len(rec["avals"]):
            diags.append(Diagnostic(
                "TPU203",
                f"eager op {name!r} holds {rec['n']} jitted variants, "
                f"{len(rec['statics'])} distinct static-arg "
                "signatures: python scalars are fragmenting the per-op "
                "cache",
                site=f"eager:{name}",
                hint="move changing scalars into tensors, or wrap the "
                     "loop in paddle.jit.to_static / incubate."
                     "lazy_eager() to amortize dispatch"))
        else:
            diags.append(Diagnostic(
                "TPU202",
                f"eager op {name!r} holds {rec['n']} jitted variants "
                f"across {len(rec['avals'])} input-shape signatures",
                site=f"eager:{name}",
                hint="bucket input shapes, or trace the loop with "
                     "paddle.jit.to_static"))
    return diags


def _diff_segment_variants(a, b, labels):
    """Name the node whose structural key differs between two compiled
    variants of the same op sequence; returns (op_name, kind, detail)
    with kind in {"scalar", "shape", "structural", "leaves"}."""
    for pos, (ka, kb) in enumerate(zip(a["keys"], b["keys"])):
        if ka == kb:
            continue
        op = labels[pos] if pos < len(labels) else f"node#{pos}"
        # dispatch node keys: (name, code, statics, attr_sig, aval_sig
        # [, hoisted]) — statics drift = baked-in python scalar
        if (isinstance(ka, tuple) and isinstance(kb, tuple)
                and len(ka) == len(kb) and len(ka) >= 5):
            if ka[2] != kb[2] or ka[3] != kb[3]:
                changed = sorted(
                    set(ka[2]) ^ set(kb[2])
                    | set(ka[3]) ^ set(kb[3]),
                    key=repr)[:4]
                return op, "scalar", repr(changed)
            if ka[4] != kb[4]:
                return op, "shape", f"{ka[4]} vs {kb[4]}"
        return op, "structural", ""
    if a["leaf_sig"] != b["leaf_sig"]:
        drift = [(i, x, y) for i, (x, y) in
                 enumerate(zip(a["leaf_sig"], b["leaf_sig"]))
                 if x != y][:3]
        return "segment leaves", "leaves", repr(drift)
    return "segment", "structural", ""


def audit_segment_cache(history=None, threshold=None, only_labels=None):
    """TPU205: segment cache thrash in the lazy eager tier.

    Groups the compile history by op-name sequence; a group that
    compiled ``threshold``+ distinct fingerprints is thrashing — steady
    state should be a pure replay.  The per-node key diff names the
    offending node so the hint can be actionable."""
    if history is None:
        from ..core.lazy import _segment_history
        history = _segment_history
    if threshold is None:
        try:
            threshold = int(os.environ.get(
                "PADDLE_TPU_EAGER_FRAG_THRESHOLD", "16"))
        except (TypeError, ValueError):
            threshold = 16
    groups = defaultdict(dict)     # labels -> {fingerprint: entry}
    for ent in list(history):
        labels = ent["labels"]
        if only_labels is not None and labels != only_labels:
            continue
        groups[labels].setdefault(ent["fingerprint"], ent)
    diags = []
    for labels, by_fp in groups.items():
        # two variants minimum to diff, even when the caller (the live
        # watch in core.lazy) has already decided the group is over
        if len(by_fp) < max(threshold, 2):
            continue
        variants = list(by_fp.values())
        op, kind, detail = _diff_segment_variants(
            variants[-2], variants[-1], labels)
        site = (f"lazy:{labels[0]}..{labels[-1]}"
                f"[{len(labels)} nodes]") if labels else "lazy:segment"
        if kind == "scalar":
            msg = (f"lazy segment ({len(labels)} nodes) compiled "
                   f"{len(by_fp)} fingerprint variants; node {op!r} "
                   f"bakes a python scalar into its key (changed "
                   f"statics: {detail})")
            hint = ("pass the changing scalar as a 0-d tensor "
                    "(paddle.to_tensor(x)) so it rides as a traced "
                    "leaf instead of a static constant")
        elif kind in ("shape", "leaves"):
            msg = (f"lazy segment ({len(labels)} nodes) compiled "
                   f"{len(by_fp)} fingerprint variants; {op!r} sees "
                   f"drifting input shapes ({detail})")
            hint = ("pad or bucket inputs to a fixed shape set; every "
                    "new shape pays a whole-segment XLA compile")
        else:
            msg = (f"lazy segment ({len(labels)} nodes) compiled "
                   f"{len(by_fp)} fingerprint variants at node {op!r}")
            hint = ("the op stream itself varies per iteration; keep "
                    "value-dependent control flow out of the steady "
                    "state or raise PADDLE_TPU_LAZY_MAX_NODES")
        diags.append(Diagnostic(
            "TPU205", msg, site=site, hint=hint,
            data={"variants": len(by_fp), "nodes": len(labels),
                  "offending_node": op, "kind": kind}))
    return diags


def audit_weak_types(closed_jaxpr, site=""):
    """TPU201: weak-typed inputs retrace when the literal context
    changes (a python float promotes differently against f32 vs bf16)."""
    diags = []
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    weak = []
    for i, var in enumerate(jaxpr.invars):
        aval = getattr(var, "aval", None)
        if getattr(aval, "weak_type", False):
            weak.append((i, str(getattr(aval, "dtype", "?"))))
    if weak:
        diags.append(Diagnostic(
            "TPU201",
            f"{len(weak)} weak-typed program input(s) "
            f"{weak[:4]}: python-number promotion decides their dtype "
            "per trace",
            site=site,
            hint="cast explicitly (astype/to_tensor with dtype) at the "
                 "program boundary"))
    return diags
