"""Host-sync analyzer: the TPU3xx family, read off the obs timeline.

The async pipeline (PR 4) made ``Executor.run(..., return_numpy=False)``
non-blocking and moved the sync point to the first host read of a
``FetchHandle`` — which records a ``cat="d2h"`` span with step
attribution.  Dispatches record ``cat="dispatch"`` spans.  That is
enough evidence to find the two classic serializers without any new
instrumentation:

* **TPU301 early read** — a d2h sync for step N landing before step
  N+1 was dispatched: the host blocked on the value it just launched,
  so device compute and host work never overlap (the pattern
  ``loss = exe.run(...); print(float(loss))`` in a loop).
* **TPU302 budget** — more d2h syncs attributed to one step than the
  per-step budget (``PADDLE_TPU_LINT_SYNC_BUDGET``, default 2: one
  loss read + one metric read).

Run it over ``obs.get_timeline().events()`` after a few steps of the
real loop; both diagnostics aggregate (one record per pattern, worst
offenders listed) instead of flagging every event.
"""
from __future__ import annotations

import os
from collections import Counter

from .diagnostics import Diagnostic

__all__ = ["audit_host_sync", "sync_budget"]


def sync_budget(default=2):
    try:
        return int(os.environ.get("PADDLE_TPU_LINT_SYNC_BUDGET",
                                  default))
    except ValueError:
        return default


def audit_host_sync(events=None, budget=None, site="step loop"):
    """TPU301/TPU302 over a list of timeline events."""
    if events is None:
        from .. import observability as obs
        events = obs.get_timeline().events()
    if budget is None:
        budget = sync_budget()

    dispatches = sorted(
        (e for e in events
         if getattr(e, "cat", None) == "dispatch"
         and getattr(e, "dur", None) is not None),
        key=lambda e: e.ts)
    d2h = sorted(
        (e for e in events if getattr(e, "cat", None) == "d2h"),
        key=lambda e: e.ts)
    diags = []
    if not d2h:
        return diags

    # -- TPU301: reads that land in the gap before the next dispatch --
    early = []
    starts = [d.ts for d in dispatches]
    for e in d2h:
        # the dispatch this read follows
        idx = None
        for i, ts in enumerate(starts):
            if ts <= e.ts:
                idx = i
            else:
                break
        if idx is None or idx + 1 >= len(dispatches):
            continue  # before the loop, or after the last step: fine
        launched = dispatches[idx]
        nxt = dispatches[idx + 1]
        if e.ts >= nxt.ts:
            continue
        same_step = (e.step is not None and launched.step is not None
                     and e.step == launched.step)
        if same_step or (e.step is None and launched.step is None):
            early.append(e)
    if early:
        names = [e.name for e in early[:4]]
        diags.append(Diagnostic(
            "TPU301",
            f"{len(early)} d2h sync(s) of a step's own fetch before the "
            f"next step was dispatched (e.g. {names}): the pipeline "
            "serializes to depth 1",
            site=site,
            hint="keep FetchHandles un-read until the value is needed "
                 "(log every k steps), or raise "
                 "PADDLE_TPU_PIPELINE_DEPTH overlap by deferring "
                 ".numpy()/float() calls",
            data={"early_reads": len(early)}))

    # -- TPU302: per-step sync counts over budget ----------------------
    per_step = Counter(e.step for e in d2h if e.step is not None)
    over = {s: n for s, n in per_step.items() if n > budget}
    if over:
        worst = sorted(over.items(), key=lambda kv: -kv[1])[:4]
        diags.append(Diagnostic(
            "TPU302",
            f"{len(over)} step(s) exceeded the per-step host-sync "
            f"budget of {budget} (worst: "
            f"{', '.join(f'step {s}: {n} syncs' for s, n in worst)})",
            site=site,
            hint="batch metric reads (fetch once, slice on host) or "
                 "raise PADDLE_TPU_LINT_SYNC_BUDGET if the reads are "
                 "intentional",
            data={"budget": budget, "steps_over": len(over)}))
    return diags
