"""Dtype/AMP analyzer: the TPU4xx family, walked over a traced jaxpr.

TPU performance is dtype-shaped: bf16 matmuls run the MXU at full rate,
f32 at half, and f64 only exists as a software emulation.  This module
walks a program's jaxpr (including sub-jaxprs of pjit/scan/cond/
custom_vjp equations) and reports:

* **TPU401** — f32 ``dot_general``/``conv`` in a program that also
  runs bf16 ones: under autocast that means an op escaped the AMP
  white list and is paying the half-rate path.  ``amp="bfloat16"``
  makes the check unconditional; ``amp="auto"`` (default) infers a
  bf16 program from the presence of bf16 matmuls.
* **TPU402** — float64 values anywhere in the program.  The global
  x64 mode (paddle-parity int64/float64 semantics) makes stray f64
  reachable from any python float, which is exactly why it needs
  flagging: on TPU it is emulated.  Severity stays "warning" because
  CPU traces legitimately carry f64 scalars.
* **TPU403** — collective equations with f64 payloads (emulated math
  *and* 2x wire bytes).  The runtime side — payload dtype/shape
  mismatches across a tensor list — is ``check_collective_payload``,
  called from the communication wrapper.
"""
from __future__ import annotations

from collections import Counter

from .diagnostics import Diagnostic

__all__ = ["iter_eqns", "audit_jaxpr", "check_collective_payload"]

_DOT_PRIMS = {"dot_general", "conv_general_dilated"}
_COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "all_gather", "all_to_all",
                     "ppermute", "reduce_scatter", "psum_scatter"}


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(item, "eqns"):
                yield item


def iter_eqns(jaxpr):
    """All equations of a jaxpr, sub-jaxprs included (pre-order)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _out_dtype(eqn):
    for var in eqn.outvars:
        aval = getattr(var, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None:
            return str(dt)
    return None


def audit_jaxpr(closed_jaxpr, *, amp="auto", site=""):
    """TPU401/402/403 over one traced program."""
    f64_prims = Counter()
    dot_dtypes = Counter()
    bad_collectives = Counter()
    for eqn in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        dt = _out_dtype(eqn)
        if dt == "float64":
            f64_prims[prim] += 1
        if prim in _DOT_PRIMS and dt is not None:
            dot_dtypes[dt] += 1
        if prim in _COLLECTIVE_PRIMS:
            for var in eqn.invars:
                adt = str(getattr(getattr(var, "aval", None), "dtype",
                                  ""))
                if adt == "float64":
                    bad_collectives[prim] += 1

    diags = []
    f32_dots = dot_dtypes.get("float32", 0)
    bf16_dots = dot_dtypes.get("bfloat16", 0) + dot_dtypes.get(
        "float16", 0)
    mixed = amp in ("bfloat16", "float16") and f32_dots \
        or (amp == "auto" and bf16_dots and f32_dots)
    if mixed:
        diags.append(Diagnostic(
            "TPU401",
            f"{f32_dots} f32 matmul/conv equation(s) alongside "
            f"{bf16_dots} low-precision one(s): ops escaped the AMP "
            "white list and run the MXU at half rate",
            site=site,
            hint="check amp.auto_cast coverage (custom_white_list) or "
                 "cast the op's inputs explicitly",
            data={"f32_dots": f32_dots, "bf16_dots": bf16_dots}))
    if f64_prims:
        top = ", ".join(f"{p} x{n}" for p, n in
                        f64_prims.most_common(4))
        diags.append(Diagnostic(
            "TPU402",
            f"{sum(f64_prims.values())} float64 equation(s) in the "
            f"program ({top}): TPU emulates f64 in software",
            site=site,
            hint="cast inputs/literals to float32, or run with "
                 "PADDLE_TPU_X32=1 to canonicalize the whole process",
            data={"f64_eqns": sum(f64_prims.values())}))
    for prim, n in bad_collectives.items():
        diags.append(Diagnostic(
            "TPU403",
            f"collective {prim} carries float64 payload(s) x{n}: "
            "emulated math plus double wire bytes",
            site=site,
            hint="reduce in float32 (cast before the collective)"))
    return diags


def check_collective_payload(op, tensors, *, group=None):
    """Runtime TPU403 check for one collective call: mixed dtypes or
    shapes across the payload list, or wide (f64/i64-beyond-need)
    floats.  Returns diagnostics (caller records them)."""
    infos = []
    for t in tensors:
        v = getattr(t, "_value", t)
        shape = tuple(getattr(v, "shape", ()))
        dtype = str(getattr(v, "dtype", "?"))
        infos.append((shape, dtype))
    diags = []
    site = f"collective:{op}"
    dtypes = {d for _, d in infos}
    shapes = {s for s, _ in infos}
    if len(infos) > 1 and (len(dtypes) > 1 or len(shapes) > 1):
        diags.append(Diagnostic(
            "TPU403",
            f"{op} payload list mixes shapes/dtypes "
            f"({sorted(dtypes)}, {len(shapes)} shapes): ranks must "
            "agree element-wise or the collective deadlocks/corrupts",
            site=site,
            hint="make every rank pass identically-shaped, "
                 "identically-typed tensors"))
    if "float64" in dtypes:
        diags.append(Diagnostic(
            "TPU403",
            f"{op} payload is float64: emulated math plus double wire "
            "bytes",
            site=site,
            hint="cast to float32 before the collective"))
    return diags
