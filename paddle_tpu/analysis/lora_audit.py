"""Multi-LoRA serving analyzers: TPU509 / TPU510, pure arithmetic.

The paged adapter store (``inference/serving/lora.py``) and the
segmented SGMV epilogue (``ops/pallas_grouped.py``) each have one
failure mode decidable before any chip time is spent:

* the STORE holds ``num_slots`` adapters in HBM and spills the rest to
  host RAM; a tenant mix whose *working set* exceeds the pool turns
  every admission into a spill + promote DMA on the decode path —
  **TPU509**.  The audit replays a request trace through the store's
  exact LRU policy, so a planned trace answers the question a live
  ``serving.lora_hit_rate`` gauge answers after the fact;
* the KERNEL packs every adapter at ``lora_rank_pad(rank, dtype)``
  (the Mosaic sublane floor: 8 rows f32, 16 bf16, 32 int8), so a rank
  below the floor zero-pads each stack and the low-rank dots multiply
  the padding — **TPU510** quantifies the wasted fraction (a rank-4
  bf16 adapter does 75% dead work; bump the rank or keep f32 stacks).

Both are callable from the lint CLI over a planned config as easily as
from a live trace.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .diagnostics import Diagnostic, DiagnosticReport, record

__all__ = ["audit_adapter_working_set", "audit_lora_rank",
           "simulate_adapter_store"]


def simulate_adapter_store(trace, num_slots):
    """Replay ``trace`` (request adapter ids, ``None`` = base model)
    through the store's LRU policy: hit = adapter already resident,
    miss = a promote, spill = an eviction the promote forced.  Returns
    ``(hits, misses, spills)``.  Matches `LoRAAdapterStore` exactly for
    serial traces (the common planning case: refcounts don't pin)."""
    lru = OrderedDict()
    hits = misses = spills = 0
    for name in trace:
        if name is None:
            continue
        if name in lru:
            lru.move_to_end(name)
            hits += 1
            continue
        misses += 1
        if len(lru) >= max(int(num_slots), 1):
            lru.popitem(last=False)
            spills += 1
        lru[name] = True
    return hits, misses, spills


def audit_adapter_working_set(trace, num_slots, *, bytes_per_slot=None,
                              threshold=0.5, site="lora.store",
                              report=None, emit=True):
    """TPU509: does the HBM slot pool hold this tenant mix's working
    set?

    ``trace`` is a sequence of per-request adapter names (``None``
    rows are base-model traffic and don't touch the store) — a planned
    tenant mix, or the replay of a live one.  Flags when the simulated
    LRU hit rate lands below ``threshold`` AND the distinct-adapter
    count actually exceeds the pool (a cold-start miss per adapter is
    not thrash).  With ``bytes_per_slot`` the finding also quantifies
    the promote traffic per 1k requests."""
    report = report if report is not None else DiagnosticReport(
        label="lora adapter working set")
    names = [t for t in trace if t is not None]
    distinct = len(set(names))
    hits, misses, spills = simulate_adapter_store(trace, num_slots)
    total = hits + misses
    rate = hits / total if total else 1.0
    data = {"num_slots": int(num_slots), "distinct": distinct,
            "requests": total, "hit_rate": round(rate, 3),
            "spills": spills, "threshold": float(threshold)}
    if bytes_per_slot and total:
        data["promote_mb_per_1k"] = round(
            misses * float(bytes_per_slot) / total * 1000 / 2**20, 1)
    if distinct > int(num_slots) and rate < threshold:
        traffic = (f", ~{data['promote_mb_per_1k']} MB promoted per 1k "
                   "requests" if "promote_mb_per_1k" in data else "")
        d = Diagnostic(
            "TPU509",
            f"{distinct} distinct adapters over {num_slots} HBM slots: "
            f"simulated LRU hit rate {rate:.0%} (threshold "
            f"{threshold:.0%}), {spills} spills over {total} "
            f"adapter-carrying requests{traffic}",
            site=site,
            hint="raise PADDLE_TPU_LORA_STORE_BUDGET (or enable_lora("
                 "num_slots=...)) toward the working set, or shard hot "
                 "tenants across replicas so each store sees a subset",
            data=data)
        if emit:
            record(d)
        report.add(d)
    return report


def audit_lora_rank(rank, dtype="float32", *, site="lora.rank",
                    report=None, emit=True):
    """TPU510: does ``rank`` reach the dtype's minimum sublane tile?

    The packed stacks always tile at ``lora_rank_pad(rank, dtype)``
    rows; a rank below that floor is stored — and multiplied — as
    zeros.  Quantifies ``1 - rank / r_pad`` (the dead fraction of both
    SGMV dots and of every adapter's HBM slot)."""
    import jax.numpy as jnp

    from ..ops.pallas_grouped import lora_rank_pad
    from ..ops.pallas_tiles import _min_rows

    report = report if report is not None else DiagnosticReport(
        label="lora rank tiling")
    jdtype = jnp.dtype(dtype)
    floor = _min_rows(jdtype)
    r_pad = lora_rank_pad(rank, jdtype)
    if int(rank) < floor:
        waste = 1.0 - int(rank) / r_pad
        d = Diagnostic(
            "TPU510",
            f"rank {rank} below the {jdtype.name} sublane floor "
            f"{floor}: stacks pad to r={r_pad}, {waste:.0%} of the "
            "SGMV rank dimension (and of every HBM slot) is zeros",
            site=site,
            hint=f"raise the rank to {floor} (free capacity — the "
                 "padding is already paid for), or keep the stacks in "
                 "float32 where the floor is 8",
            data={"rank": int(rank), "r_pad": int(r_pad),
                  "floor": int(floor), "dtype": jdtype.name,
                  "waste_frac": round(waste, 3)})
        if emit:
            record(d)
        report.add(d)
    return report
