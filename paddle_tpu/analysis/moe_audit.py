"""MoE routing analyzers: TPU507 / TPU508, pure arithmetic.

Two routers ship in the tree and each has one failure mode decidable
from geometry plus a load sample, before any chip time is spent:

* the CAPACITY router (``incubate/.../moe_layer.py``) drops every
  token past slot ``C`` of its expert (``keep = loc < C``).  Whether a
  configured ``C`` survives a given load skew is one inequality:
  ``C >= imbalance * tokens * top_k / num_experts`` — **TPU507**
  otherwise (quality silently degrades, no error is raised anywhere);
* the DROPLESS router (``distributed/auto_parallel/moe_dispatch.py``)
  never drops, but every expert's rows round up to whole
  ``block_rows`` grouped blocks, so a hot expert converts imbalance
  into padded blocks the grouped kernel still multiplies — **TPU508**
  when ``max(counts) / mean(counts)`` crosses the threshold (the same
  gauge `moe_dispatch.expert_imbalance` reports and the bench
  publishes as ``moe_gpt_expert_imbalance``).

Both are callable from the lint CLI over a planned config as easily as
from a live run's measured counts.
"""
from __future__ import annotations

import numpy as np

from .diagnostics import Diagnostic, DiagnosticReport, record

__all__ = ["audit_expert_capacity", "audit_routing_balance"]


def audit_expert_capacity(tokens, num_experts, top_k, capacity, *,
                          imbalance=2.0, site="moe.capacity",
                          report=None, emit=True):
    """TPU507: does ``capacity`` hold the expected peak expert load?

    ``imbalance`` is the load-skew factor to provision for (peak =
    ``imbalance * tokens * top_k / num_experts``); 2.0 is the usual
    early-training skew.  The incubate default ``capacity_factor=1.2``
    therefore flags here unless the gate keeps routing balanced."""
    report = report if report is not None else DiagnosticReport(
        label="moe capacity")
    mean = tokens * top_k / max(num_experts, 1)
    peak = imbalance * mean
    if capacity < peak:
        dropped = int(peak - capacity) * num_experts
        d = Diagnostic(
            "TPU507",
            f"capacity {capacity} per expert < expected peak load "
            f"{peak:.0f} ({imbalance:g}x the mean {mean:.0f} of "
            f"{tokens} tokens x top-{top_k} over {num_experts} "
            f"experts): ~{dropped} assignments dropped per step at "
            "that skew",
            site=site,
            hint="raise capacity_factor, or switch the layer to the "
                 "dropless grouped path (models/moe_gpt.py), which "
                 "pads instead of dropping",
            data={"capacity": int(capacity), "peak": round(peak, 1),
                  "mean": round(mean, 1), "tokens": int(tokens),
                  "top_k": int(top_k), "num_experts": int(num_experts),
                  "imbalance": float(imbalance)})
        if emit:
            record(d)
        report.add(d)
    return report


def audit_routing_balance(counts, *, block_rows=None, threshold=2.0,
                          site="moe.routing", report=None, emit=True):
    """TPU508: is the measured per-expert load skewed past
    ``threshold``?

    ``counts`` is the per-expert assignment histogram (the third
    return of `moe_dispatch.dropless_plan`, or any measured sample).
    With ``block_rows`` the finding also quantifies the grouped-buffer
    padding the skew costs (``padded_rows / real_rows - 1``)."""
    report = report if report is not None else DiagnosticReport(
        label="moe routing balance")
    c = np.asarray(counts, dtype=np.float64)
    total = float(c.sum())
    mean = total / max(len(c), 1)
    ratio = float(c.max()) / max(mean, 1.0)
    data = {"counts": [int(v) for v in c],
            "imbalance": round(ratio, 3),
            "threshold": float(threshold)}
    if block_rows:
        padded = float(np.ceil(c / block_rows).sum() * block_rows)
        data["padding_frac"] = round(padded / max(total, 1.0) - 1.0, 3)
    if ratio > threshold:
        pad = (f", {data['padding_frac']:.0%} grouped-block padding"
               if "padding_frac" in data else "")
        d = Diagnostic(
            "TPU508",
            f"hottest expert carries {ratio:.2f}x the mean load "
            f"(threshold {threshold:g}x{pad}): dropless blocks pad, "
            "capacity routers drop",
            site=site,
            hint="check the router aux loss is applied "
                 "(MoEGPTPretrainingCriterion weights it in) and that "
                 "its weight has not been zeroed; a dead router at "
                 "init also shows up here",
            data=data)
        if emit:
            record(d)
        report.add(d)
    return report
