"""tpu_lint: static program analysis for TPU programs.

Inspects programs *before dispatch* and emits structured ``Diagnostic``
records with stable codes (TPU1xx tiling, TPU2xx recompile risk,
TPU3xx host sync, TPU4xx dtype/precision), severity, site and fix
hint.  Entry points:

* ``Executor.analyze_program(...)`` / ``to_static fn.analyze_program()``
  — lint a program/step as it would run;
* ``scripts/tpu_lint.py --models`` — CLI over the bundled models;
* ``analysis.tiling.check_pallas_call`` — validate a kernel block plan
  (``ops/pallas_gate.py`` uses it to diagnose probe failures);
* ``analysis.analyze_runtime()`` — audit the live process (timeline,
  executable caches) after steps ran;
* ``observability.lint_summary_table()`` — render recorded findings.
"""
from . import (diagnostics, dtype_audit, fabric_audit, host_sync,
               lora_audit, moe_audit, recompile, sharding_audit, tiling)
from .diagnostics import (CODES, ERROR, INFO, SEVERITIES, WARNING,
                          Diagnostic, DiagnosticLog, DiagnosticReport,
                          describe_code, get_log, record, reset_log)
from .dtype_audit import audit_jaxpr, check_collective_payload, iter_eqns
from .fabric_audit import audit_fabric_handoff, handoff_bytes_per_block
from .fault_lint import audit_fault_sites, scan_fault_references
from .lora_audit import (audit_adapter_working_set, audit_lora_rank,
                         simulate_adapter_store)
from .moe_audit import audit_expert_capacity, audit_routing_balance
from .host_sync import audit_host_sync, sync_budget
from .sharding_audit import audit_sharding, check_collective_axis
from .program import analyze_runtime, analyze_traced, lint_summary
from .recompile import (audit_eager_cache, audit_executor_cache,
                        audit_trace_cache, audit_weak_types)
from .tiling import (LANE, VMEM_BYTES, audit_flash_attention,
                     audit_grouped_matmul, audit_layer_norm_residual,
                     audit_lora_sgmv, audit_matmul_epilogue,
                     audit_paged_attention,
                     audit_ragged_attention, check_block_spec,
                     check_pallas_call, estimate_vmem_bytes, min_tile)

__all__ = [
    "CODES", "ERROR", "INFO", "LANE", "SEVERITIES", "VMEM_BYTES",
    "WARNING", "Diagnostic", "DiagnosticLog", "DiagnosticReport",
    "analyze_runtime", "analyze_traced", "audit_adapter_working_set",
    "audit_eager_cache",
    "audit_executor_cache", "audit_expert_capacity",
    "audit_fabric_handoff",
    "audit_fault_sites", "audit_flash_attention",
    "audit_grouped_matmul", "audit_host_sync",
    "audit_jaxpr", "audit_layer_norm_residual", "audit_lora_rank",
    "audit_lora_sgmv", "audit_matmul_epilogue",
    "audit_paged_attention", "audit_ragged_attention",
    "audit_routing_balance",
    "audit_sharding", "audit_trace_cache", "check_collective_axis",
    "audit_weak_types", "check_block_spec", "check_collective_payload",
    "check_pallas_call", "describe_code", "diagnostics", "dtype_audit",
    "estimate_vmem_bytes", "fabric_audit", "get_log",
    "handoff_bytes_per_block", "host_sync", "iter_eqns",
    "lint_summary", "lora_audit", "min_tile", "moe_audit", "record",
    "recompile",
    "reset_log", "scan_fault_references", "simulate_adapter_store",
    "sync_budget", "tiling",
]
