"""Fabric handoff analyzer: TPU506, pure arithmetic.

The cross-host serving fabric (inference/serving/transport.py) is
scheduled to hide KV handoff transfers behind the destination's decode
steps — the same overlap discipline the tile-level collective overlap
uses for matmul reduce-scatters.  Whether a given payload CAN hide is
decidable before any byte moves:

* a handoff ships ``num_blocks`` cross-layer block slabs of
  ``bytes_per_block`` each, so the wire occupies the link for
  ``transfer_ms = num_blocks * bytes_per_block / link``;
* the destination keeps decoding its other rows while the payload is
  in flight, but only until the handed-off request's first decode
  step needs the blocks seated.  Under chunked prefill that window is
  the time the source spends on one admission chunk —
  ``chunk_size // block_size`` block-steps of decode at
  ``decode_step_ms`` each — because the router places payloads once
  per step and the next chunk's completion wants the previous
  payload's seat.

``transfer_ms > window_ms`` means decode stalls on the fabric:
**TPU506**.  The fix levers are the ones in the inequality — fewer
bytes per block (int8 KV halves it, scales included), a bigger chunk
(wider window), or a fatter link.
"""
from __future__ import annotations

from .diagnostics import Diagnostic, DiagnosticReport, record

__all__ = ["audit_fabric_handoff", "handoff_bytes_per_block"]


def handoff_bytes_per_block(num_layers, num_heads, block_size, head_dim,
                            itemsize, scale_lanes=0):
    """Wire bytes of ONE cross-layer block slab in a
    :class:`~..inference.serving.tiering.HandoffPayload`: K and V for
    every layer, plus the f32 per-slot scale tables int8 pools carry
    alongside."""
    data = 2 * num_layers * num_heads * block_size * head_dim * itemsize
    scales = 2 * num_layers * block_size * scale_lanes * 4
    return int(data + scales)


def audit_fabric_handoff(num_blocks, bytes_per_block, chunk_size,
                         block_size, *, link_gbps=2.0,
                         decode_step_ms=2.0, site="fabric.handoff",
                         report=None, emit=True):
    """TPU506 check for one handoff geometry (module doc).

    Pure arithmetic — no timeline, no engine: callable from the lint
    CLI over a planned serving config as easily as from a live router.
    Returns a :class:`DiagnosticReport`; the finding's ``data`` holds
    both sides of the inequality so the report is actionable."""
    report = report if report is not None else DiagnosticReport(
        label="fabric handoff")
    transfer_ms = (num_blocks * bytes_per_block) \
        / (link_gbps * 1e9) * 1e3
    window_steps = max(1, int(chunk_size) // max(1, int(block_size)))
    window_ms = window_steps * float(decode_step_ms)
    if transfer_ms > window_ms:
        d = Diagnostic(
            "TPU506",
            f"handoff of {num_blocks} blocks "
            f"({num_blocks * bytes_per_block} B) needs "
            f"{transfer_ms:.3f} ms on a {link_gbps:g} GB/s link but "
            f"the decode window at chunk size {chunk_size} is only "
            f"{window_steps} step(s) = {window_ms:.3f} ms — decode "
            "stalls on the fabric",
            site=site,
            hint="shrink bytes/block (int8 KV halves the slab, scale "
                 "tables ride along), raise the prefill chunk size to "
                 "widen the decode window, or provision link "
                 "bandwidth",
            data={"num_blocks": int(num_blocks),
                  "bytes_per_block": int(bytes_per_block),
                  "transfer_ms": round(transfer_ms, 3),
                  "window_ms": round(window_ms, 3),
                  "window_steps": window_steps,
                  "chunk_size": int(chunk_size),
                  "link_gbps": float(link_gbps)})
        if emit:
            record(d)
        report.add(d)
    return report
