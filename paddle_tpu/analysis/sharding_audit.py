"""TPU5xx: SPMD sharding lint over a MeshPlan + named parameters.

Three checks, all cheap (no tracing, no devices — a *virtual*
``MeshPlan`` works, so ``scripts/tpu_lint.py`` runs them on a
single-device host):

* **TPU501** — a parameter matched by no partition rule.  The executor
  replicates it silently; on a real mesh that is usually a forgotten
  rule, not a choice.  Only fires when the plan HAS rules (an empty
  rule set means pure data parallelism where replication is the plan).
* **TPU502** — a parameter larger than
  ``PADDLE_TPU_LINT_REPLICATED_BYTES`` (default 1 MiB) that resolves to
  a fully-replicated layout while the mesh has a model/fsdp axis of
  size > 1: every device pays the full HBM cost of a buffer the mesh
  could split.
* **TPU503** — a collective payload whose leading dim is not divisible
  by the mesh axis (group) size: scatter/alltoall-class ops get ragged
  shards or a padded transfer.  ``distributed/communication/ops.py``
  calls :func:`check_collective_axis` per payload.
* **TPU504** — a hot-path tensor-parallel matmul whose collective is
  not overlap-eligible: either the token dim does not divide by the
  ``tp`` tile count (ragged last tile forces the sequential path), or
  ``PADDLE_TPU_OVERLAP`` disables overlap outright while the mesh has
  tp > 1.  Either way the MXU idles for the full transfer; the
  message shows the tile arithmetic so the fix (pad/resize, or flip
  the flag) is obvious.  :func:`audit_overlap`.
"""
from __future__ import annotations

import os

from .diagnostics import Diagnostic, DiagnosticReport

__all__ = ["ENV_REPLICATED_THRESHOLD", "replicated_threshold",
           "audit_overlap", "audit_sharding", "check_collective_axis"]

ENV_REPLICATED_THRESHOLD = "PADDLE_TPU_LINT_REPLICATED_BYTES"
_SPLIT_OPS = ("scatter", "alltoall", "alltoall_single", "reduce_scatter")


def replicated_threshold():
    try:
        return int(os.environ.get(ENV_REPLICATED_THRESHOLD, 1 << 20))
    except ValueError:
        return 1 << 20


def audit_sharding(plan, named_params, site=""):
    """TPU501/TPU502 over ``named_params`` = ``[(name, shape, nbytes)]``
    against a :class:`~...sharding.MeshPlan`.  Returns a list of
    ``Diagnostic``; the caller decides whether to record them."""
    out = []
    if plan is None or not named_params:
        return out
    model_axes = [a for a in ("tp", "fsdp")
                  if plan.axis_sizes.get(a, 1) > 1]
    threshold = replicated_threshold()
    for name, shape, nbytes in named_params:
        matched, spec = plan.match(name, shape)
        if plan.rules and not matched:
            out.append(Diagnostic(
                "TPU501",
                f"param {name!r} {tuple(shape)} matched no partition "
                f"rule on mesh {plan.describe()}; it will be replicated",
                site=site or name,
                hint="add a rule for it (or an explicit catch-all "
                     "('.*', PartitionSpec()) if replication is "
                     "intended)",
                data={"param": name, "shape": list(shape)}))
            continue
        if (model_axes and nbytes > threshold
                and plan.shard_factor(spec) == 1):
            out.append(Diagnostic(
                "TPU502",
                f"param {name!r} ({nbytes / 2**20:.1f} MiB) is fully "
                f"replicated on mesh {plan.describe()} — axis "
                f"{model_axes} could split it",
                site=site or name,
                hint=f"shard a divisible dim over {model_axes}, or "
                     f"raise {ENV_REPLICATED_THRESHOLD} if replication "
                     "is intended",
                data={"param": name, "nbytes": int(nbytes)}))
    return out


def _spec_axes(spec):
    """Flat set of mesh-axis names a PartitionSpec entry list uses."""
    axes = set()
    for e in tuple(spec or ()):
        if e is None:
            continue
        for a in (e if isinstance(e, (tuple, list)) else (e,)):
            axes.add(a)
    return axes


def audit_overlap(plan, named_params, tokens_hint=None, site=""):
    """TPU504 over TP-sharded 2-D matmul weights.

    ``tokens_hint`` is the hot-path row count feeding those matmuls
    (tokens per device step — batch*seq after dp splitting).  Two ways
    a weight's collective loses its overlap:

    * the row dim doesn't divide by the tp tile count — the ragged
      last tile forces the padded sequential path; the diagnostic
      shows the tile arithmetic;
    * ``PADDLE_TPU_OVERLAP`` forces sequential while the mesh has
      tp > 1 — every TP matmul's collective runs with the MXU idle.

    Cheap and virtual-plan safe (pure arithmetic, no devices).
    """
    from ..distributed.auto_parallel import overlap as _ov
    out = []
    if plan is None or not named_params:
        return out
    tp = plan.axis_sizes.get("tp", 1)
    if tp <= 1:
        return out
    forced_seq = _ov.overlap_flag() == "sequential"
    for name, shape, nbytes in named_params:
        if len(tuple(shape)) != 2:
            continue
        matched, spec = plan.match(name, shape)
        if not matched or "tp" not in _spec_axes(spec):
            continue
        if forced_seq:
            out.append(Diagnostic(
                "TPU504",
                f"TP matmul weight {name!r} {tuple(shape)}: "
                f"{_ov.ENV_OVERLAP}=sequential pins its collective to "
                f"the non-overlapped path on mesh {plan.describe()}",
                site=site or name,
                hint=f"unset {_ov.ENV_OVERLAP} (auto probes the mesh) "
                     "or set it to overlap",
                data={"param": name, "shape": list(shape),
                      "tp": int(tp), "reason": "flag"}))
            continue
        if tokens_hint is None:
            continue
        if not _ov.overlap_eligible(tokens_hint, tp):
            out.append(Diagnostic(
                "TPU504",
                f"TP matmul weight {name!r} {tuple(shape)}: token dim "
                f"{int(tokens_hint)} doesn't tile over tp={tp} "
                f"({_ov.tile_arithmetic(tokens_hint, tp)}); the ring "
                "falls back to the padded sequential schedule",
                site=site or name,
                hint="size batch*seq to a multiple of the tp axis so "
                     "tiles stay even and the collective hides under "
                     "compute",
                data={"param": name, "shape": list(shape),
                      "tokens": int(tokens_hint), "tp": int(tp),
                      "tile_arithmetic":
                          _ov.tile_arithmetic(tokens_hint, tp),
                      "reason": "ragged"}))
    return out


def check_collective_axis(op_name, tensors, group_size, site=""):
    """TPU503: payload leading dims must divide by the axis (group)
    size for scatter/alltoall/reduce_scatter-class collectives."""
    out = []
    if not group_size or group_size <= 1:
        return out
    if not any(op_name.startswith(p) for p in _SPLIT_OPS):
        return out
    for t in tensors:
        shape = tuple(getattr(getattr(t, "_value", t), "shape", ()) or ())
        if not shape:
            continue
        if shape[0] % group_size != 0:
            out.append(Diagnostic(
                "TPU503",
                f"{op_name}: payload dim0 {shape[0]} not divisible by "
                f"group size {group_size} (shape {shape})",
                site=site or op_name,
                hint="pad the payload (or size the batch) to a "
                     "multiple of the mesh axis",
                data={"op": op_name, "shape": list(shape),
                      "group_size": int(group_size)}))
    return out


def audit_report(plan, named_params, label=""):
    """Convenience: run :func:`audit_sharding` into a fresh report."""
    rep = DiagnosticReport(label=label or "sharding")
    rep.extend(audit_sharding(plan, named_params, site=label))
    return rep
