"""Orchestrators: one call per artifact kind, one report out.

``analyze_traced`` is the shared backend of
``Executor.analyze_program()`` and ``TracedFunction.analyze_program()``
— it takes an already-traced ``ClosedJaxpr`` (tracing is the caller's
job: ``jax.make_jaxpr`` over the cached pure function + avals, no XLA
compile) and runs the static audits.  ``analyze_runtime`` inspects the
live process (timeline events, executable caches) after some steps ran.
``lint_summary`` is the compact dict bench.py attaches to its JSON.
"""
from __future__ import annotations

from collections import Counter

from .diagnostics import DiagnosticReport, get_log
from .dtype_audit import audit_jaxpr
from .host_sync import audit_host_sync
from .recompile import (audit_eager_cache, audit_executor_cache,
                        audit_trace_cache, audit_weak_types)

__all__ = ["analyze_traced", "analyze_runtime", "lint_summary"]


def analyze_traced(closed_jaxpr, label="", *, amp="auto",
                   executor_cache=None, trace_cache=None, emit=True,
                   mesh_plan=None, named_params=None):
    """Static audits over one traced program: weak types (TPU201),
    dtype/amp (TPU4xx), plus cache-churn audits when the owning cache
    is provided, plus sharding audits (TPU501/502) when the executor
    compiled under a mesh plan (``named_params`` is its
    ``[(name, shape, nbytes)]`` parameter inventory).  ``emit=True``
    records every finding to the process diagnostic log and the
    observability timeline."""
    report = DiagnosticReport(label=label)
    report.extend(audit_weak_types(closed_jaxpr, site=label))
    report.extend(audit_jaxpr(closed_jaxpr, amp=amp, site=label))
    if executor_cache is not None:
        report.extend(audit_executor_cache(executor_cache))
    if trace_cache is not None:
        report.extend(audit_trace_cache(trace_cache))
    if mesh_plan is not None and named_params:
        from .sharding_audit import audit_sharding
        report.extend(audit_sharding(mesh_plan, named_params,
                                     site=label))
    if emit:
        report.emit()
    return report


def analyze_runtime(events=None, budget=None, emit=True):
    """Audit the live process after steps ran: host-sync patterns over
    the obs timeline (TPU301/302) and churn in the executor + eager
    caches (TPU2xx)."""
    report = DiagnosticReport(label="runtime")
    report.extend(audit_host_sync(events, budget=budget))
    report.extend(audit_executor_cache())
    report.extend(audit_eager_cache())
    if emit:
        report.emit()
    return report


def lint_summary(events=None):
    """Compact lint state for artifacts: diagnostic counts by code
    (process log + a fresh non-emitting host-sync pass over ``events``)
    and per-kernel Pallas probe outcomes with the fallback reason."""
    counts = Counter(get_log().counts())
    if events is not None:
        for d in audit_host_sync(events):
            counts[d.code] += 1
    pallas = {}
    try:
        from ..ops.pallas_gate import probe_report
        for name, info in probe_report().items():
            # unprobed kernels are reported too — an all-fallback run
            # must be visible in the artifact, not an empty dict
            if not info.get("probed"):
                pallas[name] = {"probed": False}
                continue
            pallas[name] = {"probed": True, "ok": info["ok"]}
            if not info["ok"]:
                pallas[name]["error"] = (info.get("error") or "")[:200]
    except Exception:
        pass
    return {"counts": dict(counts), "pallas": pallas}
