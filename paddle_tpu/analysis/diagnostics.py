"""Diagnostic records: the stable currency of the tpu_lint analyzers.

Every analyzer (tiling legality, recompile risk, host-sync, dtype/amp
audit) emits ``Diagnostic`` objects with a stable code (``TPU1xx`` =
Pallas/Mosaic tiling, ``TPU2xx`` = recompile risk, ``TPU3xx`` =
host-device synchronization, ``TPU4xx`` = dtype/precision), a severity,
the site it was found at, and a fix hint.  ``DiagnosticReport`` is the
ordered collection the orchestrators and the CLI consume.

Runtime-emitted diagnostics (a Pallas probe failure diagnosed at
dispatch time, a mismatched collective payload) append to the bounded
process-wide ``DiagnosticLog`` and surface as ``cat="analysis"``
instants on the observability timeline, so fallbacks show up in traces
instead of vanishing.

Import discipline: this module may import only observability (which
itself imports nothing from paddle_tpu) — every layer records into the
log without cycles.
"""
from __future__ import annotations

import json
import threading
from collections import Counter, deque

from .. import observability as obs

__all__ = ["ERROR", "WARNING", "INFO", "SEVERITIES", "CODES",
           "Diagnostic", "DiagnosticReport", "DiagnosticLog",
           "describe_code", "get_log", "record", "reset_log"]

ERROR = "error"
WARNING = "warning"
INFO = "info"
# rank order for --fail-on comparisons (higher = more severe)
SEVERITIES = {INFO: 0, WARNING: 1, ERROR: 2}

# The stable code registry: code -> (title, default severity).  The
# README diagnostic table and the CLI --explain output render from this.
CODES = {
    # -- Pallas / Mosaic tiling legality (TPU1xx) ----------------------
    "TPU101": ("BlockSpec tile below the dtype's minimum sublane×lane "
               "shape ((8,128) f32, (16,128) bf16, (32,128) int8)", ERROR),
    "TPU102": ("grid does not cover the array: a block dim neither "
               "equals nor divides the padded array dim", ERROR),
    "TPU103": ("estimated VMEM working set exceeds the ~16 MB/core "
               "budget", ERROR),
    "TPU104": ("array crossing the pallas_call boundary has rank < 2 "
               "(Mosaic lays out the last two dims)", WARNING),
    "TPU110": ("Pallas kernel failed its probe compile; dispatch falls "
               "back to the XLA composite", WARNING),
    # -- recompile risk (TPU2xx) ---------------------------------------
    "TPU201": ("weak-typed program input (python scalar promotion): "
               "dtype context changes retrace", WARNING),
    "TPU202": ("executable-cache churn from input shape drift: same "
               "program recompiled per shape", WARNING),
    "TPU203": ("python scalar baked into the trace key as a static "
               "constant: every new value recompiles", WARNING),
    "TPU204": ("program structure mutated in place: fingerprint churn "
               "rebuilds the cached executable", WARNING),
    "TPU205": ("lazy segment cache thrash: one op sequence keeps "
               "fingerprinting to new segments instead of replaying a "
               "cached executable", WARNING),
    # -- host synchronization (TPU3xx) ---------------------------------
    "TPU301": ("early fetch read: a d2h sync lands before the next step "
               "is dispatched, serializing the pipeline", WARNING),
    "TPU302": ("per-step host-sync budget exceeded", WARNING),
    # -- dtype / precision (TPU4xx) ------------------------------------
    "TPU401": ("fp32 matmul/conv under bf16 autocast: op escaped the "
               "AMP white list and runs at half MXU rate", WARNING),
    "TPU402": ("float64 value in the program: TPU emulates f64 in "
               "software", WARNING),
    "TPU403": ("collective payload dtype/shape mismatch (or a software-"
               "emulated wide dtype) on the wire", WARNING),
    "TPU404": ("per-channel int8 scale overflow: a quantization scale is "
               "nonfinite, zero, or collapses the channel to a constant",
               WARNING),
    "TPU405": ("int8 matmul lowered onto a plan whose tiles are not "
               "(32, 128)-legal: the int8 operand forces a relayout",
               WARNING),
    # -- SPMD sharding (TPU5xx) ----------------------------------------
    "TPU501": ("parameter matched by no partition rule: silently "
               "replicated on every device of the mesh", WARNING),
    "TPU502": ("large parameter fully replicated under an fsdp/tp "
               "mesh: every device pays its full HBM cost", WARNING),
    "TPU503": ("collective payload dimension not divisible by the mesh "
               "axis size: ragged shards or a padded transfer", WARNING),
    "TPU504": ("hot-path tensor-parallel matmul whose collective cannot "
               "overlap with compute: the MXU idles for the full "
               "transfer", WARNING),
    "TPU505": ("mesh shrink dropped a model-parallel axis to replication: "
               "the surviving devices cannot hold the axis, so its "
               "parameters re-materialize fully replicated", WARNING),
    "TPU506": ("KV handoff payload cannot hide behind the decode window: "
               "the transfer outlasts the decode steps available before "
               "the destination needs the blocks, so decode stalls on "
               "the fabric", WARNING),
    "TPU507": ("expert capacity below the expected peak load: tokens "
               "past slot C of a hot expert are silently dropped by the "
               "capacity router", WARNING),
    "TPU508": ("expert routing imbalance: a hot expert's load is far "
               "above the mean, so dropless grouped blocks pad (wasted "
               "MXU cycles) and capacity routers drop", WARNING),
    "TPU509": ("adapter-store thrash: the live adapter working set "
               "exceeds the HBM slot pool, so the store keeps spilling "
               "and re-promoting adapters on the decode path", WARNING),
    "TPU510": ("LoRA rank below the dtype's minimum sublane tile: the "
               "packed stacks zero-pad every adapter to the tile floor "
               "and the SGMV dots multiply the padding", WARNING),
    # -- fault-site registry (TPU6xx) ----------------------------------
    "TPU601": ("fault-site reference not in the FAULT_SITES registry: "
               "chaos schedules can never reach it, and a typo'd site "
               "silently never fires", ERROR),
    "TPU602": ("registered fault site with no fault_point() "
               "instrumentation anywhere in the tree: schedules list "
               "it but injection can never trigger", WARNING),
}


def describe_code(code):
    """(title, default severity) for a stable code; KeyError if unknown."""
    return CODES[code]


class Diagnostic:
    """One finding: stable code, severity, site, message, fix hint."""

    __slots__ = ("code", "severity", "message", "site", "hint", "data")

    def __init__(self, code, message, *, site="", hint="", severity=None,
                 data=None):
        if code not in CODES:
            raise ValueError(f"unknown diagnostic code {code!r}")
        self.code = code
        self.severity = severity or CODES[code][1]
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")
        self.message = message
        self.site = site
        self.hint = hint
        self.data = dict(data or {})

    def to_dict(self):
        d = {"code": self.code, "severity": self.severity,
             "message": self.message, "site": self.site}
        if self.hint:
            d["hint"] = self.hint
        if self.data:
            d["data"] = self.data
        return d

    def __repr__(self):
        return (f"Diagnostic({self.code} {self.severity} @{self.site}: "
                f"{self.message})")


class DiagnosticReport:
    """Ordered collection of diagnostics with summary/render helpers."""

    def __init__(self, diagnostics=(), label=""):
        self.label = label
        self._diags = list(diagnostics)

    def __iter__(self):
        return iter(self._diags)

    def __len__(self):
        return len(self._diags)

    def __getitem__(self, i):
        return self._diags[i]

    @property
    def diagnostics(self):
        return list(self._diags)

    def add(self, diag):
        self._diags.append(diag)

    def extend(self, diags):
        for d in diags:
            self.add(d)
        return self

    def by_code(self, code):
        return [d for d in self._diags if d.code == code]

    def errors(self):
        return [d for d in self._diags if d.severity == ERROR]

    def warnings(self):
        return [d for d in self._diags if d.severity == WARNING]

    def counts(self):
        """{code: count}, the compact summary bench.py records."""
        return dict(Counter(d.code for d in self._diags))

    def max_severity(self):
        if not self._diags:
            return None
        return max((d.severity for d in self._diags),
                   key=lambda s: SEVERITIES[s])

    def ok(self, fail_on=ERROR):
        """True when no diagnostic reaches the ``fail_on`` severity."""
        if fail_on in (None, "never"):
            return True
        bar = SEVERITIES[fail_on]
        return all(SEVERITIES[d.severity] < bar for d in self._diags)

    def to_json(self):
        return json.dumps({"label": self.label,
                           "diagnostics": [d.to_dict() for d in self]},
                          indent=1)

    def render(self, limit=None):
        """Text table: CODE SEVERITY SITE MESSAGE (+ hint lines)."""
        head = f"== {self.label or 'lint'}: " + (
            "clean" if not self._diags else
            f"{len(self.errors())} error(s), "
            f"{len(self.warnings())} warning(s), "
            f"{len(self._diags)} total")
        lines = [head]
        for d in self._diags[:limit]:
            lines.append(f"  {d.code} [{d.severity:<7}] {d.site}: "
                         f"{d.message}")
            if d.hint:
                lines.append(f"      hint: {d.hint}")
        if limit is not None and len(self._diags) > limit:
            lines.append(f"  ... {len(self._diags) - limit} more")
        return "\n".join(lines)

    def emit(self):
        """Record every diagnostic: bounded process log + obs instant."""
        for d in self._diags:
            record(d)
        return self


class DiagnosticLog:
    """Bounded process-wide log of runtime-emitted diagnostics."""

    def __init__(self, capacity=1024):
        self._lock = threading.Lock()
        self._buf = deque(maxlen=capacity)

    def append(self, diag):
        with self._lock:
            self._buf.append(diag)

    def events(self):
        with self._lock:
            return list(self._buf)

    def counts(self):
        with self._lock:
            return dict(Counter(d.code for d in self._buf))

    def clear(self):
        with self._lock:
            self._buf.clear()


_log = DiagnosticLog()


def get_log():
    """The process-wide diagnostic log (probe fallbacks, runtime checks)."""
    return _log


def reset_log():
    _log.clear()


def record(diag):
    """Append to the process log and mark the observability timeline."""
    _log.append(diag)
    if obs.enabled():
        obs.instant("lint:" + diag.code, cat="analysis",
                    severity=diag.severity, site=diag.site,
                    message=diag.message)
    return diag
