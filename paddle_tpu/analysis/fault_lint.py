"""Fault-site registry lint: TPU601/602, pure AST.

The chaos explorer (distributed/fault_tolerance/chaos.py) can only
schedule faults at sites listed in the central ``FAULT_SITES``
registry, and a ``fault_point("store.gett")`` typo fails *silently* —
the injection hook just never fires and the test passes vacuously.
This pass closes both gaps statically:

* **TPU601** (error) — a literal fault-site reference
  (``fault_point(...)``, ``FaultEvent(...)``, ``plan.add(site,
  action)``, or a compact ``FaultPlan.parse``/``inject`` spec) names a
  site no registry pattern matches.  Register it or fix the typo.
* **TPU602** (warning) — a registry pattern that no scanned
  ``fault_point()`` call can ever satisfy: schedules will list the
  site but injection can never trigger.  Dead registry entries rot
  into false chaos coverage.

Dynamic sites are handled conservatively: an f-string or string
concatenation collapses its dynamic parts to ``*``, which matches only
a wildcard ``<...>`` registry segment (``f"fabric.host_down.h{i}"`` →
``fabric.host_down.h*`` → ``fabric.host_down.<host>``).  A site built
entirely at runtime (plain variable) is skipped — the lint only
judges what it can read.
"""
from __future__ import annotations

import ast
import os

from .diagnostics import Diagnostic, DiagnosticReport, record
from ..distributed.fault_tolerance.plan import (FAULT_SITES, FaultPlan,
                                                _ACTIONS, matching_sites)

__all__ = ["audit_fault_sites", "iter_source_files",
           "scan_fault_references"]

# repo-relative scan roots: every tree that references fault sites
_SCAN_DIRS = ("paddle_tpu", "scripts", "tests")
_SCAN_FILES = ("bench.py",)


def _literal_site(node):
    """Best-effort literal for a site expression.  Constant strings come
    back verbatim; f-string / ``+``-concat dynamic parts collapse to
    ``*`` (matches only a wildcard registry segment); anything else is
    ``None`` — not judgeable, skipped."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(v.value if isinstance(v, ast.Constant)
                       and isinstance(v.value, str) else "*"
                       for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _literal_site(node.left)
        right = _literal_site(node.right)
        if left is None and right is None:
            return None
        return (left if left is not None else "*") \
            + (right if right is not None else "*")
    return None


def _func_name(call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def scan_fault_references(path):
    """All judgeable fault-site references in one python file, as
    ``(site, lineno, kind)`` tuples.  ``kind`` is the call shape that
    produced the reference; only ``fault_point`` counts as
    *instrumentation* for TPU602 coverage — the other shapes are
    demand-side (schedules and plans)."""
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return []
    refs = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _func_name(node)
        args = node.args
        if name in ("fault_point", "FaultEvent") and args:
            site = _literal_site(args[0])
            if site is not None and "." in site:
                refs.append((site, node.lineno, name))
        elif name == "add" and len(args) >= 2:
            # FaultPlan.add(site, action): claim the shape only when the
            # second arg is a literal action verb, so set.add / report
            # .add and friends never trip it.
            site = _literal_site(args[0])
            action = _literal_site(args[1])
            if site is not None and action in _ACTIONS and "." in site:
                refs.append((site, node.lineno, "plan.add"))
        elif name in ("parse", "inject") and args:
            spec = args[0]
            if (isinstance(spec, ast.Constant)
                    and isinstance(spec.value, str)
                    and ":" in spec.value):
                try:
                    plan = FaultPlan.parse(spec.value)
                except Exception:
                    continue  # not a fault spec (or a malformed one —
                    #           the call site's own test covers that)
                refs.extend((ev.site, node.lineno, name)
                            for ev in plan.events)
    return refs


def iter_source_files(root):
    """Every ``.py`` under the scan roots, deterministic order."""
    for d in _SCAN_DIRS:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(x for x in dirnames
                                 if not x.startswith(".")
                                 and x != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in _SCAN_FILES:
        p = os.path.join(root, fn)
        if os.path.isfile(p):
            yield p


def audit_fault_sites(root=None, *, report=None, emit=True):
    """TPU601/602 over the whole tree (module doc).  Pure AST — no
    imports of the scanned files, so a module with heavy import-time
    side effects lints the same as any other."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    report = report if report is not None else DiagnosticReport(
        label="fault sites")
    covered = set()
    for path in iter_source_files(root):
        rel = os.path.relpath(path, root)
        for site, lineno, kind in scan_fault_references(path):
            pats = matching_sites(site)
            if pats:
                if kind == "fault_point":
                    covered.update(pats)
                continue
            d = Diagnostic(
                "TPU601",
                f"{kind} references fault site {site!r} which no "
                "FAULT_SITES registry pattern matches — a chaos "
                "schedule can never reach it and a typo here fails "
                "silently",
                site=f"{rel}:{lineno}",
                hint="register the site in distributed/fault_tolerance/"
                     "plan.py FAULT_SITES (register_fault_site) or fix "
                     "the site string",
                data={"ref_site": site, "kind": kind, "path": rel,
                      "lineno": int(lineno)})
            if emit:
                record(d)
            report.add(d)
    for pat in sorted(FAULT_SITES):
        if pat in covered:
            continue
        d = Diagnostic(
            "TPU602",
            f"registered fault site {pat!r} has no fault_point() "
            "instrumentation anywhere in the tree — schedules list it "
            "but injection can never trigger",
            site=f"FAULT_SITES[{pat!r}]",
            hint="add a fault_point() at the code path the entry "
                 "describes, or drop the dead registry entry",
            data={"pattern": pat})
        if emit:
            record(d)
        report.add(d)
    return report
