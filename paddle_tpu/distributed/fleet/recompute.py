"""Activation recompute (gradient checkpointing).

Reference parity: `python/paddle/distributed/fleet/recompute/recompute.py`
(PyLayer that reruns forward in backward, preserving RNG state)
[UNVERIFIED — empty reference mount].

TPU-native: jax.checkpoint (remat) on the pure op-sequence — XLA reruns the
forward inside the backward pass; RNG is deterministic because the
generator key threads through as data (SURVEY.md §2.3 mapping).
"""
from __future__ import annotations

import jax

from ...core.dispatch import dispatch
from ...core.tensor import Tensor
from ...core import autograd as _ag

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              **kwargs):
    """Run `function` under rematerialization.

    The callable is re-traced as a pure jax function of its tensor args
    (+ captured params via closure), wrapped with jax.checkpoint so the
    backward pass recomputes activations instead of storing them.
    """
    if not _ag.is_grad_enabled():
        return function(*args, **kwargs)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensors = [args[i] for i in tensor_idx]
    # capture the parameters the function reads so remat sees them as
    # differentiable inputs too
    from ...nn.layer.layers import Layer

    params = []
    # a Layer passed directly (`recompute(blk, x)`) owns its params just
    # like a bound method's __self__ does — without this, layer-call
    # remat silently dropped every parameter gradient
    fn_self = function if isinstance(function, Layer) \
        else getattr(function, "__self__", None)
    if isinstance(fn_self, Layer):
        params = [p for p in fn_self.parameters() if not p.stop_gradient]

    n_args = len(tensors)

    def pure(*vals):
        arg_vals = vals[:n_args]
        param_vals = vals[n_args:]
        # rebind: swap values into fresh Tensors / params temporarily
        new_args = list(args)
        for i, v in zip(tensor_idx, arg_vals):
            new_args[i] = Tensor(v, _internal=True,
                                 stop_gradient=args[i].stop_gradient)
        saved = [(p, p._value) for p in params]
        try:
            for p, v in zip(params, param_vals):
                p._value = v
            out = function(*new_args, **kwargs)
        finally:
            for p, v in saved:
                p._value = v
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(pure)
    return dispatch("recompute", lambda *vals: ckpt(*vals),
                    tuple(tensors) + tuple(params), {})


def recompute_sequential(ctx, functions, *args, **kwargs):
    """recompute_sequential({'segments': k}, Sequential(...), input)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    seg_size = max(len(layers) // max(segments, 1), 1)
    x = args[0]
    i = 0
    while i < len(layers):
        seg = layers[i:i + seg_size]

        def run_seg(t, seg=seg):
            for l in seg:
                t = l(t)
            return t

        x = recompute(run_seg, x, **kwargs)
        i += seg_size
    return x
