"""Fleet: the distributed training facade.

Reference parity: `python/paddle/distributed/fleet/` (fleet.py facade,
base/topology.py HybridCommunicateGroup, base/distributed_strategy.py)
[UNVERIFIED — empty reference mount].
"""
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from .fleet_facade import (init, is_first_worker, worker_index, worker_num,
                           is_worker, worker_endpoints, server_num,
                           distributed_model, distributed_optimizer,
                           get_hybrid_communicate_group, barrier_worker,
                           init_worker, stop_worker, save_persistables)
from . import meta_parallel
from .recompute import recompute, recompute_sequential
from .utils import log_util

utils = log_util
