"""Context parallelism: ring attention + Ulysses over a `sep` mesh axis.

Reference parity: the hybrid topology's `sep` degree
(`fleet/base/topology.py`) with ring/Ulysses attention implementations
historically shipped in PaddleNLP (`ring_flash_attention`) [UNVERIFIED —
empty reference mount; SURVEY.md §2.3 SEP/CP row, §5 "first-class
here"].

TPU-native design (SURVEY.md §5): the sequence dim is sharded over the
`sep` mesh axis.

* **Ring attention**: each device holds its Q shard permanently and the
  K/V shards rotate around the ICI ring with `jax.lax.ppermute`, one hop
  per step; a blockwise online-softmax accumulates (m, l, acc) so the
  result is exact attention over the full sequence with only
  S_local-sized K/V resident per step.  Causal masking uses global
  positions, so arbitrary shard counts work.  The per-step block matmuls
  are MXU-shaped einsums; compute of step r overlaps the permute of step
  r+1 under XLA's latency-hiding scheduler.
* **Ulysses**: two `all_to_all`s redistribute heads↔sequence so each
  device runs full-sequence attention over H/sep heads locally (the
  local attention can take the Pallas flash path).

Both are exposed as
  - `*_local` functions to call INSIDE shard_map / pjit-sharded code;
  - global convenience wrappers that shard_map over the current mesh.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ...env import global_mesh
from ...jax_compat import shard_map as _shard_map

__all__ = ["ring_attention_local", "ring_attention",
           "ulysses_attention_local", "ulysses_attention"]

_NEG_INF = -1e30


def ring_attention_local(q, k, v, *, axis="sep", axis_size, causal=False,
                         scale=None, use_pallas=None):
    """Exact blockwise attention; call inside shard_map.

    q/k/v: local shards [B, S_local, H, D] (Paddle layout).  Returns the
    local output shard [B, S_local, H, D].

    On TPU (Pallas gate open) each resident KV block runs through the
    Mosaic flash kernels with an exact ring backward
    (ops/ring_flash_attention.py); this jnp blockwise path is the
    fallback and the numerics oracle.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if use_pallas is None:
        from ....ops.pallas_gate import pallas_enabled
        use_pallas = pallas_enabled("flash_attention")
    if use_pallas:
        from ....ops.ring_flash_attention import ring_flash_attention_local
        return ring_flash_attention_local(
            q, k, v, axis=axis, axis_size=axis_size, causal=causal,
            scale=scale)
    me = jax.lax.axis_index(axis)
    B, S_loc, H, D = q.shape
    qs = jnp.swapaxes(q, 1, 2).astype(jnp.float32)      # B H Sq D
    k_cur = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    v_cur = jnp.swapaxes(v, 1, 2).astype(jnp.float32)

    row = me * S_loc + jnp.arange(S_loc)                # global q rows
    m = jnp.full((B, H, S_loc, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S_loc, 1), jnp.float32)
    acc = jnp.zeros((B, H, S_loc, D), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for r in range(axis_size):
        src = (me - r) % axis_size                      # owner of k_cur
        col = src * S_loc + jnp.arange(S_loc)           # global kv cols
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = col[None, :] <= row[:, None]         # (Sq, Sk) global
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur,
            preferred_element_type=jnp.float32)
        m = m_new
        if r != axis_size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)                      # B S H D


def ulysses_attention_local(q, k, v, *, axis="sep", axis_size,
                            causal=False, scale=None, dropout_p=0.0):
    """Ulysses: all_to_all heads↔sequence, full-seq attention locally.

    Requires num_heads % axis_size == 0.  Call inside shard_map with
    local shards [B, S_local, H, D]; returns [B, S_local, H, D].
    """
    B, S_loc, H, D = q.shape
    if H % axis_size != 0:
        raise ValueError(f"num_heads {H} not divisible by sep={axis_size}")
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                            split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)                 # B S_glob H/P D
    from ....nn.functional.flash_attention import _sdpa_ref
    out = _sdpa_ref(qg, kg, vg, None, causal,
                    scale or 1.0 / (D ** 0.5))
    return jax.lax.all_to_all(out, axis_name=axis, split_axis=1,
                              concat_axis=2, tiled=True)


_WRAPPER_CACHE: dict = {}


def _global_wrapper(local_fn, q, k, v, sep_axis, causal, scale, mesh):
    mesh = mesh or global_mesh()
    if mesh is None or sep_axis not in mesh.axis_names:
        raise ValueError(
            f"ring/ulysses attention needs a mesh with a '{sep_axis}' "
            f"axis (got {mesh and mesh.axis_names})")
    axis_size = mesh.shape[sep_axis]
    # cache the shard_mapped callable so repeated eager calls hit jax's
    # trace/compile cache instead of re-tracing the ring loop each step
    key = (local_fn, mesh, sep_axis, axis_size, causal, scale)
    fn = _WRAPPER_CACHE.get(key)
    if fn is None:
        spec = P(None, sep_axis, None, None)            # shard seq dim
        fn = _shard_map(
            functools.partial(local_fn, axis=sep_axis,
                              axis_size=axis_size, causal=causal,
                              scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        _WRAPPER_CACHE[key] = fn
    if any(isinstance(x, Tensor) for x in (q, k, v)):
        # through the dispatch layer so the eager tape records a grad
        # node (jax.vjp differentiates through shard_map/ppermute)
        from ....core.dispatch import dispatch
        from ....core.tensor import Tensor as T
        args = tuple(x if isinstance(x, T)
                     else T(jnp.asarray(x), _internal=True,
                            stop_gradient=True)
                     for x in (q, k, v))
        return dispatch(getattr(local_fn, "__name__", "ring_attention"),
                        lambda qv, kv, vv: fn(qv, kv, vv), args, {})
    return fn(*(jnp.asarray(x) for x in (q, k, v)))


def ring_attention(q, k, v, *, causal=False, scale=None, sep_axis="sep",
                   mesh=None):
    """Global-view ring attention: q/k/v [B, S, H, D] get seq-sharded
    over the sep axis; output is the global [B, S, H, D]."""
    return _global_wrapper(ring_attention_local, q, k, v, sep_axis,
                           causal, scale, mesh)


def ulysses_attention(q, k, v, *, causal=False, scale=None,
                      sep_axis="sep", mesh=None):
    """Global-view Ulysses attention (two all_to_alls + local SDPA)."""
    return _global_wrapper(ulysses_attention_local, q, k, v, sep_axis,
                           causal, scale, mesh)
