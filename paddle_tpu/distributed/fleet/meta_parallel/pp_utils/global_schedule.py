"""Global-array SPMD pipeline engine: heterogeneous stages + pp×mp×dp.

Reference parity: `fleet/meta_parallel/pp_layers.py` (PipelineLayer
segments arbitrary LayerDesc lists — embedding first stage, lm-head last,
SharedLayerDesc tying them) + `pipeline_parallel.py` (1F1B composing with
mp/dp inside the hybrid cube) [UNVERIFIED — empty reference mount;
SURVEY.md §2.3 PP row, §3.6; VERDICT r3 missing #3].

TPU-native redesign, second formulation (the first — shard_map + explicit
ppermute, spmd_schedule.py — remains for the homogeneous mp=1 case):
everything is GLOBAL sharded arrays under one jit, and XLA inserts every
collective:

  * the homogeneous trunk ("body") is detected as the longest periodic
    run of structurally identical layer groups; the leading remainder
    ("pre": embeddings, …) and trailing remainder ("post": final norm,
    lm head, loss inputs) run OUTSIDE the pipeline scan, sharded over
    dp/mp only — this lifts the identical-stages constraint: a GPT-style
    [embed, block×N, ln, tied-head] PipelineLayer pipelines its trunk
    while pre/post stay dense;
  * trunk stage parameters are stacked on a leading dim sharded over the
    `pp` mesh axis; the stage compute is a `jax.vmap` over that dim — an
    elementwise map XLA executes shard-local, with each stage's weights
    resident on its own pp slice;
  * the GPipe tick rotates a (n_stages, micro, ...) activation buffer
    with `jnp.roll` on the pp-sharded dim — XLA lowers exactly this to a
    CollectivePermute over ICI (the reference's send_v2/recv_v2);
  * tensor-parallel layers inside any section keep their NamedSharding
    placements (mp_layers.py), so pp×mp×dp composes by construction —
    the same sharding-propagation mechanism that runs them standalone;
  * fp16 GradScaler support is native: the loss is scaled in-graph,
    grads unscaled, a found_inf reduction guards the fused update, and
    the host updates the scaler's scale from the returned flag (the
    reference's update_loss_scaling op).
"""
from __future__ import annotations

import functools
import hashlib
import logging
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor

logger = logging.getLogger("paddle_tpu.pipeline")

__all__ = ["GlobalPipelineEngine"]


def _array_digest(v):
    a = np.asarray(v)
    h = hashlib.sha1(a.tobytes()).hexdigest()[:16]
    return ("ndarray", a.shape, str(a.dtype), h)


def _callable_digest(v, _depth=0):
    """Behavior-bearing identity of a callable: code object PLUS the
    values it closes over, its defaults, and (for functools.partial)
    the wrapped func + bound args — two lambdas from one factory with
    different captured constants must NOT fingerprint alike."""
    if _depth > 3:
        return ("callable_deep",)
    if isinstance(v, functools.partial):
        return ("partial", _callable_digest(v.func, _depth + 1),
                tuple(_value_digest(a, _depth + 1) for a in v.args),
                tuple(sorted((k, _value_digest(a, _depth + 1))
                             for k, a in v.keywords.items())))
    code = getattr(v, "__code__", None)
    cells = ()
    if getattr(v, "__closure__", None):
        cells = tuple(_value_digest(c.cell_contents, _depth + 1)
                      for c in v.__closure__)
    defaults = tuple(_value_digest(d, _depth + 1)
                     for d in (getattr(v, "__defaults__", None) or ()))
    return ("callable", getattr(v, "__qualname__", type(v).__name__),
            hash(code.co_code) if code else None, cells, defaults)


def _value_digest(v, _depth=0):
    if isinstance(v, (bool, int, float, str, type(None))):
        return v
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        return _array_digest(v)
    if isinstance(v, Tensor):
        return _array_digest(v._value)
    if isinstance(v, (tuple, list)):
        return tuple(_value_digest(e, _depth + 1) for e in v[:32]) \
            if _depth <= 3 else ("seq_deep", len(v))
    if callable(v):
        return _callable_digest(v, _depth)
    return ("opaque", type(v).__name__)


_warned_deep = set()


def _config_fingerprint(fn, _depth=0):
    """Config attrs (dropout p, epsilon, flags, masks, hooks, ...) of a
    layer and its sublayers: stages that differ only in parameterless
    config must NOT be treated as identical (all stages execute the
    template stage's code).  Array-valued attrs (an ndarray mask) are
    content-hashed, callables fingerprinted with their closures and
    defaults, and registered forward pre/post hooks included (VERDICT
    r4 weak #6: these previously escaped the fingerprint and could
    silently merge behaviorally different stages)."""
    if not hasattr(fn, "__dict__"):
        return ()
    if _depth > 8:
        # too deep to inspect: return a UNIQUE sentinel so such stages
        # never compare equal — loud no-merge fallback, never silent
        # wrong numerics
        key = type(fn).__name__
        if key not in _warned_deep:
            _warned_deep.add(key)
            logger.warning(
                "pipeline: %s nested deeper than 8 layers — config "
                "fingerprint gives up; stages containing it will NOT "
                "be merged into a pipeline trunk", key)
        return ("too_deep", id(fn))
    out = []
    for k, v in sorted(vars(fn).items()):
        if k.startswith("_") and k not in ("_epsilon", "_p"):
            continue
        if isinstance(v, (bool, int, float, str, type(None))):
            out.append((k, v))
        elif isinstance(v, (tuple, list)) and all(
                isinstance(e, (bool, int, float, str)) for e in v):
            out.append((k, tuple(v)))
        elif isinstance(v, (np.ndarray, jnp.ndarray)):
            out.append((k, _array_digest(v)))
        elif isinstance(v, Tensor):
            # plain Tensor attr (an ndarray mask, ...).  Parameters are
            # compared by shape/dtype in _entry_signature and buffers
            # hashed below — skip both here.
            if (k not in getattr(fn, "_parameters", {})
                    and k not in getattr(fn, "_buffers", {})):
                out.append((k, _array_digest(v._value)))
        elif callable(v) and not hasattr(v, "parameters"):
            out.append((k, _callable_digest(v)))
    # registered hooks run in __call__ and change stage math
    for store in ("_forward_pre_hooks", "_forward_post_hooks"):
        hooks = getattr(fn, store, None)
        if hooks:
            out.append((store, tuple(
                _callable_digest(h) for h in
                (hooks.values() if hasattr(hooks, "values") else hooks))))
    # THIS level's own buffers only — sublayer buffers are hashed by the
    # child's recursion (named_buffers() here would re-hash each buffer
    # once per ancestor, each hash a device->host transfer)
    bufs = getattr(fn, "_buffers", None)
    if bufs:
        for name, b in sorted(bufs.items()):
            if b is not None:
                out.append(("buf:" + name, _array_digest(b._value)))
    for name, sub in (fn.named_children()
                      if hasattr(fn, "named_children") else ()):
        out.append((name, _config_fingerprint(sub, _depth + 1)))
    return tuple(out)


def _entry_signature(entry):
    fn, fwd = entry
    name = type(fn).__name__ if not callable(fn) or hasattr(
        fn, "parameters") else getattr(fn, "__name__", "fn")
    params = fn.parameters() if hasattr(fn, "parameters") else []
    return (name, getattr(fwd, "__name__", None), tuple(
        (tuple(p.shape), str(p.dtype)) for p in params),
        _config_fingerprint(fn))


def _find_trunk(sigs, n_stages, max_edge=8):
    """Split layer signatures into (pre_len, body_len, post_len) where the
    body is periodic with some period p and repeats m ≡ 0 (mod n_stages).
    Prefers the longest body, then the smallest edge sections."""
    n = len(sigs)
    best = None
    for pre in range(0, min(max_edge, n) + 1):
        for post in range(0, min(max_edge, n - pre) + 1):
            body = n - pre - post
            if body <= 0:
                continue
            seg = sigs[pre:pre + body]
            for period in range(1, body + 1):
                if body % period:
                    continue
                reps = body // period
                if reps % n_stages:
                    continue
                if all(seg[i] == seg[i % period]
                       for i in range(body)):
                    cand = (body, -(pre + post), pre, post, period)
                    if best is None or cand > best:
                        best = cand
                    break
    if best is None:
        return None
    body, _, pre, post, period = best
    return pre, body, post


def _interleave_schedule(n_micro, pp, v):
    """Static per-tick control arrays for the interleaved schedule.

    Chunks are assigned round-robin (chunk c -> slot c % pp, phase
    c // pp).  Micros are injected in groups of pp; group g's phase-k
    chunks occupy slot 0 during ticks [g*v*pp + k*pp, ... + pp).  With
    the activation wrap riding the roll (slot pp-1 -> slot 0), no
    activation ever waits in a queue: slot 0's wrap arrival for phase
    k+1 lands exactly when its phase-k window closes.  Total ticks
    T = ((n_micro-1)//pp)*v*pp + (v-1)*pp + (n_micro-1)%pp + pp
    (the last micro's final chunk at slot pp-1, inclusive) —
    = n_micro*v + pp - 1 exactly when pp divides n_micro; a ragged
    tail finishes a few ticks sooner (its group is partially masked
    garbage).

    Returns numpy arrays (inj[T] bool, inj_m[T] i32, ext[T] bool,
    ext_m[T] i32, phase[T, pp] i32).
    """
    vp = v * pp
    g_last = (n_micro - 1) // pp
    j_last = (n_micro - 1) % pp
    t_last = g_last * vp + (v - 1) * pp + j_last + (pp - 1)
    T = t_last + 1
    ts = np.arange(T)
    inj_m = (ts // vp) * pp + (ts % pp)
    inj = ((ts % vp) < pp) & (inj_m < n_micro)
    r = ts - (pp - 1)
    ext_m = (r // vp) * pp + (np.maximum(r, 0) % pp)
    ext = (r >= 0) & ((np.maximum(r, 0) % vp) // pp == v - 1) \
        & (ext_m >= 0) & (ext_m < n_micro)
    phase = ((ts[:, None] - np.arange(pp)[None, :]) % vp) // pp
    return (inj, np.clip(inj_m, 0, n_micro - 1).astype(np.int32),
            ext, np.clip(ext_m, 0, n_micro - 1).astype(np.int32),
            phase.astype(np.int32))


class _PureSection:
    """Run an ordered list of (layer, forward_func) entries as a pure
    function of its unique parameter leaves (the tensor._value swap trick
    jit/trace.py and spmd_schedule.py use)."""

    def __init__(self, entries):
        self.entries = entries
        self.params = []
        self.buffers = []
        seen = set()
        for fn, _ in entries:
            if hasattr(fn, "parameters"):
                for p in fn.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        self.params.append(p)
            if hasattr(fn, "named_buffers"):
                for _, b in fn.named_buffers():
                    if id(b) not in seen:
                        seen.add(id(b))
                        self.buffers.append(b)

    def __call__(self, param_vals, x_val):
        from .....core.autograd import no_grad
        from .....core.tensor import swapped_values
        with swapped_values(zip(self.params, param_vals),
                            save_extra=self.buffers):
            with no_grad():
                x = Tensor(x_val, _internal=True, stop_gradient=True)
                for fn, fwd in self.entries:
                    x = fwd(fn, x) if fwd is not None else fn(x)
            return x._value


# Layer-level sharding constraints (RowParallelLinear's "replicate the
# output" etc.) assume unbatched global activations; under the trunk's
# stage-vmap they would fight the pp sharding of the stage dim.  The
# engine suspends them for the vmapped region only.
_suspend = threading.local()


def constraints_suspended():
    return getattr(_suspend, "on", False)


class _SuspendConstraints:
    def __enter__(self):
        self._prev = getattr(_suspend, "on", False)
        _suspend.on = True

    def __exit__(self, *exc):
        _suspend.on = self._prev


def _param_spec(t, extra_leading=None):
    """PartitionSpec for a parameter: its mp placement if any.
    ``extra_leading``: a single axis name, or a tuple of leading
    entries (e.g. ``("pp", None)`` for the interleave's stacked
    (pp, v, ...) layout)."""
    sh = getattr(t, "dist_spec", None)
    if isinstance(sh, NamedSharding):
        entries = tuple(sh.spec)
        entries += (None,) * (t._value.ndim - len(entries))
    else:
        entries = (None,) * t._value.ndim
    if isinstance(extra_leading, tuple):
        entries = extra_leading + entries
    elif extra_leading is not None:
        entries = (extra_leading,) + entries
    return P(*entries)


class GlobalPipelineEngine:
    """Compiled GPipe over global sharded arrays; heterogeneous pre/post
    sections; composes with mp (tensor parallel) and dp/sharding axes."""

    def __init__(self, pipeline_layer, hcg, optimizer, n_micro,
                 remat=True, n_virtual=1):
        self.pl = pipeline_layer
        self.hcg = hcg
        self.mesh = hcg.mesh
        if self.mesh is None or "pp" not in self.mesh.axis_names:
            raise ValueError("no pp axis in mesh")
        if hcg.get_sep_parallel_world_size() > 1:
            raise ValueError("sep axis inside the pipeline engine is "
                             "not supported")
        self.optimizer = optimizer
        self.n_micro = int(n_micro)
        self.n_stages = int(self.mesh.shape["pp"])
        self.n_virtual = int(n_virtual or 1)
        if self.n_virtual < 1:
            raise ValueError("n_virtual must be >= 1")
        self.remat = remat
        self._compiled = {}
        self._step_host = 0
        self._dirty = False

        # Interleave (n_virtual = v > 1): the trunk is cut into
        # pp*v chunks assigned ROUND-ROBIN — chunk c lives on pp slot
        # c % pp as its phase c // pp.  Per schedule tick each slot
        # computes exactly ONE chunk (its weights selected by a
        # per-slot phase GATHER on a (pp, v, ...) stacked dim — data
        # movement, not a serial loop over chunks), so a tick costs
        # 1/v of a full-stage tick and the fill/drain bubble shrinks
        # from (pp-1) full-stage ticks to (pp-1) chunk ticks — the
        # Megatron virtual-stage bubble reduction, in one SPMD scan.
        n_chunks = self.n_stages * self.n_virtual
        entries = list(pipeline_layer.run_function)
        # intern the (deep) signature tuples to small ints: _find_trunk
        # compares only equality, and the unbounded retry below is
        # O(n^2) splits x O(body) comparisons
        canon = {}
        sigs = [canon.setdefault(_entry_signature(e), len(canon))
                for e in entries]
        split = _find_trunk(sigs, n_chunks)
        if split is None:
            # the fast path bounds pre/post at 8 layers; a model with a
            # deeper head/tail is legitimate — retry unbounded, loudly
            # (VERDICT r4 weak #6: the bound used to fail silent)
            split = _find_trunk(sigs, n_chunks, max_edge=len(sigs))
            if split is not None:
                logger.warning(
                    "pipeline(global): trunk found only with pre/post "
                    "sections deeper than 8 layers (pre=%d post=%d); "
                    "these run OUTSIDE the pipeline on every rank",
                    split[0], split[2])
        if split is None:
            raise ValueError(
                "no periodic trunk divisible into "
                f"{n_chunks} chunks ({self.n_stages} stages x "
                f"{self.n_virtual} virtual) in {len(entries)} layers "
                "(stages that differ in config, masks, buffers or "
                "callable attrs are never merged; use spmd_schedule "
                "or adjust the layer list)")
        pre_n, body_n, post_n = split
        per_chunk_n = body_n // n_chunks
        self.pre = _PureSection(entries[:pre_n])
        self.post = _PureSection(entries[pre_n + body_n:])
        chunk_entries = [
            entries[pre_n + c * per_chunk_n:
                    pre_n + (c + 1) * per_chunk_n]
            for c in range(n_chunks)]
        # chunk_sections[c]: model order; slot s holds chunks
        # [k*pp + s for k in range(v)] (round-robin)
        self.chunk_sections = [_PureSection(e) for e in chunk_entries]
        # kept name: at v=1 a "chunk" IS a stage (back-compat for
        # sync_params_to_layers and external introspection)
        self.stage_sections = self.chunk_sections
        self.body_template = self.chunk_sections[0]
        if any(s.buffers for s in self.chunk_sections):
            raise ValueError("trunk stages with buffers (e.g. BN "
                             "running stats) are not supported")
        n_bp = len(self.body_template.params)
        if any(len(s.params) != n_bp for s in self.chunk_sections):
            raise ValueError("stage param counts differ")
        logger.info(
            "pipeline(global): pre=%d trunk=%d (%d/chunk x %d stages "
            "x %d virtual) post=%d layers", pre_n, body_n, per_chunk_n,
            self.n_stages, self.n_virtual, post_n)

        # outer params: pre+post unique tensors (tied weights dedup here)
        outer, seen = [], set()
        for t in self.pre.params + self.post.params:
            if id(t) not in seen:
                seen.add(id(t))
                outer.append(t)
        body_ids = {id(p) for s in self.stage_sections for p in s.params}
        if body_ids & {id(t) for t in outer}:
            raise ValueError("a weight shared between trunk and "
                             "pre/post sections is not supported")
        self.outer = outer

        # trunk params stacked on a pp-sharded leading dim; with
        # virtual stages an extra REPLICATED phase dim rides second:
        # (pp, v, ...), slot s phase k = chunk k*pp + s (round-robin)
        self.stacked = []
        pp, v = self.n_stages, self.n_virtual
        for i in range(n_bp):
            if v == 1:
                arr = jnp.stack([self.chunk_sections[s].params[i]._value
                                 for s in range(pp)])
                extra = ("pp",)
            else:
                arr = jnp.stack([
                    jnp.stack([
                        self.chunk_sections[k * pp + s].params[i]._value
                        for k in range(v)])
                    for s in range(pp)])
                extra = ("pp", None)
            tpl = self.chunk_sections[0].params[i]
            spec = _param_spec(tpl, extra_leading=extra)
            arr = jax.device_put(arr, NamedSharding(self.mesh, spec))
            t = Tensor(arr, _internal=True)
            t.stop_gradient = tpl.stop_gradient
            t.name = tpl.name + "@pp_stacked"
            t.dist_spec = NamedSharding(self.mesh, spec)
            self.stacked.append(t)

        self.all_params = list(self.outer) + list(self.stacked)
        self.trainable = [t for t in self.all_params
                          if not t.stop_gradient]
        self.opt_state = optimizer._ensure_static_state(self.trainable)
        # accumulators shard like their params.  Optimizer state layouts
        # differ (Adam/Momentum: one block per accumulator kind;
        # Rprop/NAdam/...: interleaved per param, possibly with trailing
        # scalars like NAdam's mu_product), so associate by EXACT shape
        # under the candidate layouts and leave anything ambiguous
        # unsharded (correct, just resharded by XLA on first use).
        n_tr = len(self.trainable)
        n_acc = len(self.opt_state)
        k = n_acc // n_tr if n_tr and n_acc % n_tr == 0 else 0
        for i, acc in enumerate(self.opt_state):
            ash = tuple(acc._value.shape)
            cands = ([self.trainable[i % n_tr],
                      self.trainable[i // k]] if k else [])
            pt = next((c for c in cands
                       if tuple(c._value.shape) == ash), None)
            if pt is None:
                same = [t for t in self.trainable
                        if tuple(t._value.shape) == ash]
                specs = {str(getattr(t, "dist_spec", None))
                         for t in same}
                pt = same[0] if same and len(specs) == 1 else None
            if pt is None:
                continue
            sh = getattr(pt, "dist_spec", None)
            spec = (tuple(sh.spec) if isinstance(sh, NamedSharding)
                    else ())
            spec = P(*(spec + (None,) * (acc._value.ndim - len(spec))))
            acc._value = jax.device_put(
                acc._value, NamedSharding(self.mesh, spec))

        self.batch_axes = tuple(
            a for a in ("dp", "sharding") if a in self.mesh.axis_names
            and self.mesh.shape[a] > 1) or None

    # ------------------------------------------------------------------
    def _build(self, x_aval, y_aval, with_scaler):
        n_micro, n_stages = self.n_micro, self.n_stages
        mesh = self.mesh
        pre, post = self.pre, self.post
        stage_tpl = self.body_template
        loss_fn = getattr(self.pl, "_loss_fn", None)
        optimizer = self.optimizer
        trainable = self.trainable
        n_outer = len(self.outer)
        outer_train = [i for i, t in enumerate(self.outer)
                       if not t.stop_gradient]
        stacked_train = [i for i, t in enumerate(self.stacked)
                         if not t.stop_gradient]
        batch_axes = self.batch_axes
        remat = self.remat
        # Tensor.__eq__ is elementwise — index by id, never list.index
        outer_pos = {id(t): i for i, t in enumerate(self.outer)}
        pre_idx = [outer_pos[id(t)] for t in pre.params]
        post_idx = [outer_pos[id(t)] for t in post.params]

        n_virtual = self.n_virtual

        if n_virtual == 1:
            def body_one(stage_leaves, x):
                with _SuspendConstraints():
                    return stage_tpl(stage_leaves, x)

            if remat:
                body_one = jax.checkpoint(body_one)
            body_v = jax.vmap(body_one, in_axes=(0, 0))
        else:
            def chunk_one(slot_leaves, phase, x):
                # phase selects this slot's ACTIVE chunk for the tick:
                # a gather on the replicated (v, ...) dim — weight data
                # movement, not execution of all v chunks (a lax.switch
                # under vmap would compute every branch)
                leaves = tuple(
                    jax.lax.dynamic_index_in_dim(w, phase, 0,
                                                 keepdims=False)
                    for w in slot_leaves)
                with _SuspendConstraints():
                    return stage_tpl(leaves, x)

            if remat:
                chunk_one = jax.checkpoint(chunk_one)
            body_v = jax.vmap(chunk_one, in_axes=(0, 0, 0))

        def state_constraint(v, leading):
            spec = P(leading, batch_axes,
                     *([None] * (v.ndim - 2)))
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec))

        def run_loss(out_val, y_val):
            from .....core.autograd import no_grad
            with no_grad():
                o = Tensor(out_val, _internal=True, stop_gradient=True)
                l = Tensor(y_val, _internal=True, stop_gradient=True)
                r = loss_fn(o, l) if loss_fn is not None else o
            v = r._value if isinstance(r, Tensor) else r
            return jnp.mean(v.astype(jnp.float32))

        def step_fn(outer_vals, stacked_vals, opt_vals, lr, step, scale,
                    x, y):
            mb = x.shape[1]

            def loss_of(train_leaves):
                o_vals = list(outer_vals)
                s_vals = list(stacked_vals)
                k = 0
                for i in outer_train:
                    o_vals[i] = train_leaves[k]
                    k += 1
                for i in stacked_train:
                    s_vals[i] = train_leaves[k]
                    k += 1
                pre_vals = [o_vals[i] for i in pre_idx]
                post_vals = [o_vals[i] for i in post_idx]

                xf = x.reshape((n_micro * mb,) + x.shape[2:])
                h = pre(pre_vals, xf) if pre.entries else xf
                h = h.reshape((n_micro, mb) + h.shape[1:])

                state0 = jnp.zeros((n_stages,) + h.shape[1:], h.dtype)
                state0 = state_constraint(state0, "pp")
                outbuf0 = jnp.zeros_like(h)

                if n_virtual == 1:
                    def tick(carry, t):
                        state, outbuf = carry
                        x_t = jnp.where(
                            t < n_micro,
                            jax.lax.dynamic_index_in_dim(
                                h, jnp.clip(t, 0, n_micro - 1), 0,
                                keepdims=False),
                            jnp.zeros_like(h[0]))
                        state = jnp.roll(state, 1, axis=0)
                        # i32 index: a bare python 0 is i64 under the
                        # global x64 and trips the hlo verifier against
                        # the partitioner's i32 shard-offset arithmetic
                        state = jax.lax.dynamic_update_index_in_dim(
                            state, x_t, jnp.int32(0), 0)
                        state = state_constraint(state, "pp")
                        state = body_v(tuple(s_vals), state)
                        state = state_constraint(state, "pp")
                        mi = t - (n_stages - 1)
                        idx = jnp.clip(mi, 0, n_micro - 1)
                        cur = jax.lax.dynamic_index_in_dim(
                            outbuf, idx, 0, keepdims=False)
                        new = jnp.where(mi >= 0, state[n_stages - 1],
                                        cur)
                        outbuf = jax.lax.dynamic_update_index_in_dim(
                            outbuf, new, idx, 0)
                        return (state, outbuf), None

                    # i32 tick index: an i64 scan carry (global x64)
                    # collides with the partitioner's i32 offset math
                    # inside dynamic_update_slice after spmd-partitioning
                    (_, outbuf), _ = jax.lax.scan(
                        tick, (state0, outbuf0),
                        jnp.arange(n_micro + n_stages - 1,
                                   dtype=jnp.int32))
                else:
                    # Interleaved schedule (see __init__): per tick
                    # every slot computes ONE chunk, phases selected by
                    # static per-(tick, slot) index arrays.  A micro
                    # enters slot 0 whenever its phase-0 window is open,
                    # wraps pp-1 -> 0 at each phase boundary via the
                    # roll, and exits after v*pp chunk hops.  Ticks:
                    # n_micro*v + pp - 1 at ~1/v full-stage cost each.
                    sched = _interleave_schedule(
                        n_micro, n_stages, n_virtual)
                    inj, inj_m, ext, ext_m, phase = (
                        jnp.asarray(a, jnp.int32)
                        if np.asarray(a).dtype.kind in "iu"
                        else jnp.asarray(a) for a in sched)

                    def tick(carry, x_t):
                        state, outbuf = carry
                        inj_t, inj_mt, ext_t, ext_mt, phase_row = x_t
                        x_in = jax.lax.dynamic_index_in_dim(
                            h, inj_mt, 0, keepdims=False)
                        new0 = jnp.where(inj_t, x_in, state[0])
                        state = jax.lax.dynamic_update_index_in_dim(
                            state, new0, jnp.int32(0), 0)
                        state = state_constraint(state, "pp")
                        state = body_v(tuple(s_vals), phase_row, state)
                        state = state_constraint(state, "pp")
                        moved = jnp.roll(state, 1, axis=0)
                        moved = state_constraint(moved, "pp")
                        cur = jax.lax.dynamic_index_in_dim(
                            outbuf, ext_mt, 0, keepdims=False)
                        outbuf = jax.lax.dynamic_update_index_in_dim(
                            outbuf, jnp.where(ext_t, moved[0], cur),
                            ext_mt, 0)
                        return (moved, outbuf), None

                    (_, outbuf), _ = jax.lax.scan(
                        tick, (state0, outbuf0),
                        (inj, inj_m, ext, ext_m, phase))

                of = outbuf.reshape((n_micro * mb,) + outbuf.shape[2:])
                out = post(post_vals, of) if post.entries else of
                loss = run_loss(out, y.reshape((n_micro * mb,)
                                               + y.shape[2:]))
                return loss * scale

            train_leaves = tuple(
                [outer_vals[i] for i in outer_train]
                + [stacked_vals[i] for i in stacked_train])
            scaled_loss, grads = jax.value_and_grad(loss_of)(train_leaves)
            loss = scaled_loss / scale
            inv = 1.0 / scale
            grads = tuple(
                (g.astype(jnp.float32) * inv).astype(g.dtype)
                for g in grads)
            if with_scaler:
                found_inf = jnp.any(jnp.stack([
                    jnp.logical_not(jnp.all(jnp.isfinite(
                        g.astype(jnp.float32)))) for g in grads]))
            else:
                found_inf = jnp.bool_(False)

            p_in = train_leaves
            grads = optimizer._l1_grads(grads, p_in)
            new_p, new_opt = optimizer._pure_update(
                lr, step, p_in, grads, opt_vals, trainable)
            if with_scaler:
                new_p = tuple(
                    jnp.where(found_inf, o, n)
                    for o, n in zip(p_in, new_p))
                new_opt = tuple(
                    jnp.where(found_inf, o, n)
                    for o, n in zip(opt_vals, new_opt))
            # scatter updated trainables back into the full lists
            o_out = list(outer_vals)
            s_out = list(stacked_vals)
            k = 0
            for i in outer_train:
                o_out[i] = new_p[k]
                k += 1
            for i in stacked_train:
                s_out[i] = new_p[k]
                k += 1
            return (loss, found_inf, tuple(o_out), tuple(s_out),
                    tuple(new_opt))

        from .....framework.flags import get_flags
        donate = get_flags("FLAGS_buffer_donation")[
            "FLAGS_buffer_donation"]
        return jax.jit(step_fn,
                       donate_argnums=(0, 1, 2) if donate else ())

    # ------------------------------------------------------------------
    def train_step(self, x, y, lr, scale=None):
        """One pipelined step; x/y are (n_micro, mb, ...) arrays.
        Returns (loss, found_inf)."""
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        if self.batch_axes:
            x = jax.device_put(x, NamedSharding(
                self.mesh, P(None, self.batch_axes,
                             *([None] * (x.ndim - 2)))))
            y = jax.device_put(y, NamedSharding(
                self.mesh, P(None, self.batch_axes,
                             *([None] * (y.ndim - 2)))))
        with_scaler = scale is not None
        key = (x.shape, str(x.dtype), y.shape, str(y.dtype), with_scaler)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(x, y, with_scaler)
            self._compiled[key] = fn
        from .....core.lazy import concrete_values
        loss, found_inf, new_outer, new_stacked, new_opt = fn(
            concrete_values(self.outer),
            concrete_values(self.stacked),
            concrete_values(self.opt_state),
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._step_host, jnp.int32),
            jnp.asarray(1.0 if scale is None else scale, jnp.float32),
            x, y)
        for t, v in zip(self.outer, new_outer):
            t._value = v
        for t, v in zip(self.stacked, new_stacked):
            t._value = v
        for t, v in zip(self.opt_state, new_opt):
            t._value = v
        self._step_host += 1
        self._dirty = True
        return float(loss), bool(found_inf)

    def sync_params_to_layers(self):
        """Scatter trained trunk params back into the per-chunk eager
        layers (outer params are trained in place already)."""
        if not self._dirty:
            return
        pp, v = self.n_stages, self.n_virtual
        for i, st in enumerate(self.stacked):
            host = np.asarray(st._value)
            for s in range(pp):
                if v == 1:
                    self.chunk_sections[s].params[i]._value = \
                        jnp.asarray(host[s])
                else:
                    for k in range(v):
                        self.chunk_sections[k * pp + s].params[
                            i]._value = jnp.asarray(host[s, k])
        self._dirty = False
