"""SPMD pipeline schedule: GPipe over a `pp` mesh axis with ppermute.

Reference parity: `fleet/meta_parallel/pp_utils/p2p_communication.py` +
`pipeline_parallel.py`'s 1F1B loop (per-rank send/recv of activations,
microbatch steady-state interleave) [UNVERIFIED — empty reference mount;
SURVEY.md §3.6].

TPU-native redesign (SURVEY.md §2.3 PP row): in a single-controller SPMD
runtime the hand-written P2P loop becomes ONE compiled program over the
mesh:

  * stage parameters are STACKED on a leading stage dim and sharded over
    the `pp` mesh axis (each device physically holds only its stage —
    the "stage placement" the reference does with per-rank allocation);
  * the schedule is a `lax.scan` over T = n_micro + P - 1 ticks; at each
    tick every stage applies its segment to the activation it holds and
    `ppermute`s the result to the next stage over ICI (the reference's
    send_v2/recv_v2);
  * losses are computed everywhere (SPMD) and masked to the last stage's
    valid microbatches; `jax.value_and_grad` through the scan gives the
    GPipe backward (identical loss/grad math to 1F1B; 1F1B's memory win
    is recovered with `jax.checkpoint` around the stage body);
  * the optimizer update runs on the stacked, pp-sharded state in the
    same jitted step (param + opt-state buffers donated).

This module also covers the reference's **fleet executor**
(`fluid/distributed/fleet_executor/`: carrier/interceptor message-driven
per-rank section execution — SURVEY.md §2.1).  Its job — delivering
activations between pipeline sections and sequencing their execution —
is exactly what the scan+ppermute program compiles away: XLA's
scheduler sequences the sections and the ICI transfers, so there is no
runtime message loop to build.

Constraints of the SPMD formulation: every stage's segment must be
structurally identical (same layer classes, same param shapes — the
standard homogeneous-pipeline requirement) and stage output shape must
equal stage input shape.  `PipelineParallel.train_batch` verifies this
and falls back to plain microbatch gradient accumulation otherwise.
"""
from __future__ import annotations

import logging

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....communication.group import Group  # noqa: F401  (API surface)
from ....jax_compat import shard_map as _shard_map
from .....core.tensor import Tensor

logger = logging.getLogger("paddle_tpu.pipeline")

__all__ = ["SpmdPipelineEngine"]


def _stage_signature(segment):
    """Structural signature of one stage segment: layer classes + param
    shapes/dtypes + config fingerprint (homogeneity check across
    stages).  Every stage executes stage 0's CODE, so stages that
    differ in any behavior-bearing attr — scalar config, ndarray
    masks, buffers, callable hooks — must NOT be merged (VERDICT r4
    weak #6; shares global_schedule's hardened fingerprint)."""
    from .global_schedule import _config_fingerprint
    sig = []
    for fn, fwd in segment:
        name = type(fn).__name__ if not callable(fn) or hasattr(
            fn, "parameters") else getattr(fn, "__name__", "fn")
        params = fn.parameters() if hasattr(fn, "parameters") else []
        sig.append((name, getattr(fwd, "__name__", None), tuple(
            (tuple(p.shape), str(p.dtype)) for p in params),
            _config_fingerprint(fn)))
    return tuple(sig)


def _segment_tensors(segment):
    """All state tensors of a segment, params first then buffers, in a
    deterministic order."""
    params, buffers = [], []
    for fn, _ in segment:
        if hasattr(fn, "parameters"):
            params.extend(fn.parameters())
        if hasattr(fn, "named_buffers"):
            buffers.extend(b for _, b in fn.named_buffers())
    return params, buffers


class _FunctionalSegment:
    """Run a segment's Paddle layers as a pure function of its params.

    The eager layers read `tensor._value`; swapping those for traced
    values for the duration of the call turns the stage into the pure
    `stage_apply(param_vals, x)` the SPMD schedule needs (the same
    substitution trick jit/trace.py uses for to_static).
    """

    def __init__(self, segment):
        self.segment = segment
        self.params, self.buffers = _segment_tensors(segment)

    def __call__(self, param_vals, x_val):
        from .....core.autograd import no_grad
        from .....core.tensor import swapped_values
        with swapped_values(zip(self.params, param_vals),
                            save_extra=self.buffers):
            with no_grad():  # jax.grad differentiates; skip the tape
                x = Tensor(x_val, _internal=True, stop_gradient=True)
                for fn, fwd in self.segment:
                    x = fwd(fn, x) if fwd is not None else fn(x)
            return x._value


class SpmdPipelineEngine:
    """Builds + runs the compiled GPipe step for one PipelineLayer."""

    def __init__(self, pipeline_layer, hcg, optimizer, n_micro,
                 remat=True):
        self.pl = pipeline_layer
        self.hcg = hcg
        self.mesh = hcg.mesh
        self.optimizer = optimizer
        self.n_micro = int(n_micro)
        self.n_stages = pipeline_layer.get_num_stages()
        self.remat = remat
        self._compiled = {}
        self._step_host = 0
        self._dirty = False  # stacked state newer than the eager layers

        segments = [pipeline_layer.segment(s)
                    for s in range(self.n_stages)]
        sigs = {_stage_signature(s) for s in segments}
        if len(sigs) != 1:
            raise ValueError(
                "SPMD pipeline requires structurally identical stages; "
                f"got {len(sigs)} distinct stage signatures")
        self.segments = segments
        self.apply0 = _FunctionalSegment(segments[0])
        if not self.apply0.params:
            raise ValueError("pipeline stages have no parameters")

        # batch axes: every mesh axis except pp carries data
        self.batch_axes = tuple(n for n in self.mesh.axis_names
                                if n != "pp")
        self.dp_total = int(np.prod(
            [self.mesh.shape[a] for a in self.batch_axes])) or 1

        # ---- stack stage params over a leading pp-sharded dim ----
        per_stage = [_segment_tensors(s)[0] for s in segments]
        n_p = len(per_stage[0])
        stacked = []
        for i in range(n_p):
            arr = jnp.stack([per_stage[s][i]._value
                             for s in range(self.n_stages)])
            sh = NamedSharding(self.mesh,
                               P("pp", *([None] * (arr.ndim - 1))))
            stacked.append(jax.device_put(arr, sh))
        self.per_stage_params = per_stage
        self.stacked = [Tensor(a, _internal=True) for a in stacked]
        for st, t0 in zip(self.stacked, per_stage[0]):
            st.stop_gradient = t0.stop_gradient
            st.name = t0.name + "@pp_stacked"
        self.opt_state = optimizer._ensure_static_state(self.stacked)
        # reshard accumulators like their params (zeros created unsharded)
        for i, acc in enumerate(self.opt_state):
            pi = i % len(self.stacked)
            sh = NamedSharding(
                self.mesh, P("pp", *([None] * (acc._value.ndim - 1))))
            acc._value = jax.device_put(acc._value, sh)

    # ------------------------------------------------------------------
    def _build(self, x_aval, y_aval):
        n_micro, n_stages = self.n_micro, self.n_stages
        apply0 = self.apply0
        loss_fn = getattr(self.pl, "_loss_fn", None)
        mesh = self.mesh
        batch_axes = self.batch_axes
        all_axes = ("pp",) + batch_axes
        optimizer = self.optimizer
        stacked_t = self.stacked
        dp_total = self.dp_total

        def seg_apply(p_local, x):
            return apply0(p_local, x)

        if self.remat:
            seg_apply = jax.checkpoint(seg_apply)

        def run_loss(out_val, lab_val):
            from .....core.autograd import no_grad
            with no_grad():
                o = Tensor(out_val, _internal=True, stop_gradient=True)
                l = Tensor(lab_val, _internal=True, stop_gradient=True)
                r = loss_fn(o, l) if loss_fn is not None else o
            v = r._value if isinstance(r, Tensor) else r
            return v.astype(jnp.float32).reshape(())

        def device_fn(stacked, opt_vals, lr, step, x, y):
            # stacked leaves: (1, ...) local stage slice; x/y: (n_micro,
            # mb_local, ...)
            pp = jax.lax.axis_index("pp")
            p_locals = [a[0] for a in stacked]

            def local_loss(p_locals):
                def tick(carry, t):
                    state, loss_acc = carry
                    xi = jnp.clip(t, 0, n_micro - 1)
                    x_t = jnp.where(t < n_micro, x[xi],
                                    jnp.zeros_like(x[0]))
                    inp = jnp.where(pp == 0, x_t, state)
                    out = seg_apply(p_locals, inp)
                    mb = t - (n_stages - 1)
                    lab = y[jnp.clip(mb, 0, n_micro - 1)]
                    l = run_loss(out, lab)
                    valid = jnp.logical_and(
                        pp == n_stages - 1,
                        jnp.logical_and(mb >= 0, mb < n_micro))
                    loss_acc = loss_acc + jnp.where(valid, l, 0.0)
                    nxt = jax.lax.ppermute(
                        out, "pp",
                        [(i, (i + 1) % n_stages)
                         for i in range(n_stages)])
                    return (nxt, loss_acc), None

                act0 = jnp.zeros_like(x[0])
                (_, loss_sum), _ = jax.lax.scan(
                    tick, (act0, jnp.float32(0.0)),
                    jnp.arange(n_micro + n_stages - 1))
                # return the LOCAL contribution (nonzero on the last
                # stage only).  Differentiating the local value is the
                # correct SPMD formulation: every device seeds cotangent
                # 1 on its own scalar and the ppermute transposes route
                # cotangents across stages, so grads come out as
                # d(global loss)/d(local params).  Do NOT psum here —
                # under check_vma=False psum transposes to psum, which
                # multiplies every gradient by the device count.
                return loss_sum / (n_micro * dp_total)

            loss, grads = jax.value_and_grad(local_loss)(p_locals)
            loss = jax.lax.psum(loss, all_axes)  # report the global loss
            # dp-replicated params: true grad = sum of per-copy grads
            if batch_axes:
                grads = jax.lax.psum(grads, batch_axes)
            grads = optimizer._l1_grads(tuple(grads), tuple(p_locals))
            new_p, new_opt = optimizer._pure_update(
                lr, step, tuple(p_locals), tuple(grads),
                tuple(o[0] for o in opt_vals), stacked_t)
            return (loss, tuple(p[None] for p in new_p),
                    tuple(o[None] for o in new_opt))

        rep = P(*([None] * 0))
        p_specs = [P("pp", *([None] * (t._value.ndim - 1)))
                   for t in self.stacked]
        o_specs = [P("pp", *([None] * (t._value.ndim - 1)))
                   for t in self.opt_state]
        data_spec_x = P(None, batch_axes if batch_axes else None,
                        *([None] * (len(x_aval.shape) - 2)))
        data_spec_y = P(None, batch_axes if batch_axes else None,
                        *([None] * (len(y_aval.shape) - 2)))

        smapped = _shard_map(
            device_fn, mesh=mesh,
            in_specs=(tuple(p_specs), tuple(o_specs), rep, rep,
                      data_spec_x, data_spec_y),
            out_specs=(rep, tuple(p_specs), tuple(o_specs)))

        jitted = jax.jit(smapped, donate_argnums=(0, 1))
        return jitted

    # ------------------------------------------------------------------
    def train_step(self, x, y, lr):
        """One pipelined train step over a full (already micro-split)
        batch: x/y are (n_micro, mb, ...) host or device arrays."""
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        key = (x.shape, str(x.dtype), y.shape, str(y.dtype))
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                jax.ShapeDtypeStruct(y.shape, y.dtype))
            self._compiled[key] = fn
        from .....core.lazy import concrete_values
        loss, new_p, new_opt = fn(
            concrete_values(self.stacked),
            concrete_values(self.opt_state),
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._step_host, jnp.int64),
            x, y)
        for t, v in zip(self.stacked, new_p):
            t._value = v
        for t, v in zip(self.opt_state, new_opt):
            t._value = v
        self._step_host += 1
        self._dirty = True
        return float(loss)

    def sync_params_to_layers(self):
        """Scatter the trained stacked params back into the eager
        per-stage layer tensors (state_dict/save/eval visibility)."""
        if not self._dirty:
            return
        for i, st in enumerate(self.stacked):
            host = np.asarray(st._value)
            for s in range(self.n_stages):
                self.per_stage_params[s][i]._value = jnp.asarray(host[s])
        self._dirty = False
