from .spmd_schedule import SpmdPipelineEngine
from .global_schedule import GlobalPipelineEngine

__all__ = ["SpmdPipelineEngine", "GlobalPipelineEngine"]
