from .spmd_schedule import SpmdPipelineEngine

__all__ = ["SpmdPipelineEngine"]
