"""Sharding stage 1/2: shard optimizer states (and grads) over the axis.

Reference parity: `fleet/meta_parallel/sharding/group_sharded_stage2.py` +
`group_sharded_optimizer_stage2.py` [UNVERIFIED — empty reference mount].
"""
from __future__ import annotations

import jax

from .....nn import Layer
from ....env import global_mesh
from ....parallel import DataParallel
from .group_sharded import _shard_axis, shard_leading_dim

__all__ = ["GroupShardedStage2"]


class GroupShardedStage2(DataParallel):
    def __init__(self, model, optimizer, group=None, shard_grads=True,
                 **kwargs):
        super().__init__(model)
        self._optim = optimizer
        self._shard_grads = shard_grads
        self._wrap_optimizer()

    def _wrap_optimizer(self):
        """Hook the optimizer's accumulator factory so every new moment is
        placed sharded along the sharding axis."""
        mesh = global_mesh()
        axis = _shard_axis(mesh)
        if axis is None or mesh.shape[axis] <= 1:
            return
        optim = self._optim
        orig_acc = optim._acc

        def sharded_acc(name, param, init=0.0, shape=None, dtype=None):
            t = orig_acc(name, param, init, shape, dtype)
            t._value = shard_leading_dim(t._value, mesh, axis)
            return t

        optim._acc = sharded_acc

    def to(self, *args, **kwargs):
        return self
