"""Sharding stage 3: shard parameters themselves (FSDP).

Reference parity: `fleet/meta_parallel/sharding/group_sharded_stage3.py`
(param shards + allgather-on-demand + free-after-use) [UNVERIFIED — empty
reference mount].  TPU-native: parameters are *placed* sharded on the
sharding axis; XLA gathers on use and the buffers stay sharded at rest —
exactly the stage-3 dataflow, compiler-managed.
"""
from __future__ import annotations

import jax

from ....env import global_mesh
from ....parallel import DataParallel
from .group_sharded import _shard_axis, shard_leading_dim
from .group_sharded_stage2 import GroupShardedStage2

__all__ = ["GroupShardedStage3"]


class GroupShardedStage3(GroupShardedStage2):
    def __init__(self, model, optimizer, group=None, **kwargs):
        super().__init__(model, optimizer, group=group, shard_grads=True)
        self._shard_params()

    def _shard_params(self):
        mesh = global_mesh()
        axis = _shard_axis(mesh)
        if axis is None or mesh.shape[axis] <= 1:
            return
        for p in self._layers.parameters():
            p._value = shard_leading_dim(p._value, mesh, axis)

    def get_all_parameters(self):
        """Gather full params (reference: allgather + rebuild)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = global_mesh()
        rep = NamedSharding(mesh, P())
        for p in self._layers.parameters():
            try:
                p._value = jax.device_put(p._value, rep)
            except Exception:
                pass
        return self._layers.parameters()
