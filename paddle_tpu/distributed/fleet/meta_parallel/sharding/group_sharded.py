"""group_sharded_parallel: ZeRO stage 1/2/3 entry point.

Reference parity: `fleet/meta_parallel/sharding/group_sharded.py` (+
group_sharded_stage2/3, group_sharded_optimizer_stage2) [UNVERIFIED —
empty reference mount].

TPU-native (SURVEY.md §2.3 sharding row): ZeRO falls out of *sharding
specs*, not wrapper bookkeeping —
  stage 1/2: optimizer accumulators placed sharded along the dp/sharding
             axis (each chip stores 1/N of the moments);
  stage 3:   parameters themselves placed sharded; XLA all-gathers them
             on use and frees after (the stage-3 gather-on-demand).
The wrappers below apply those placements and otherwise pass through.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....nn import Layer
from ....env import global_mesh, get_world_size

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def _shard_axis(mesh):
    for cand in ("sharding", "fsdp", "dp"):
        if cand in mesh.axis_names:
            return cand
    return None


def shard_leading_dim(arr, mesh, axis):
    """Place an array sharded along its leading dim on `axis`."""
    if arr.ndim == 0:
        return arr
    n = mesh.shape[axis]
    if arr.shape[0] % n != 0:
        return arr
    sh = NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))
    try:
        return jax.device_put(arr, sh)
    except Exception:
        return arr


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    from .group_sharded_stage2 import GroupShardedStage2
    from .group_sharded_stage3 import GroupShardedStage3

    mesh = global_mesh()
    axis = _shard_axis(mesh)
    if level in ("os", "os_g"):
        wrapped = GroupShardedStage2(model, optimizer, group=group,
                                     shard_grads=(level == "os_g"))
    elif level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer, group=group)
    else:
        raise ValueError(f"unknown group_sharded level {level!r}")
    if scaler is not None:
        return wrapped, wrapped._optim, scaler
    return wrapped, wrapped._optim, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from .....framework.io import save

    target = model._layers if hasattr(model, "_layers") else model
    save(target.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
