"""Megatron-style tensor-parallel layers.

Reference parity: `fleet/meta_parallel/parallel_layers/mp_layers.py`
(VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
ParallelCrossEntropy) + `fleet/layers/mpu/mp_ops.py` (_c_identity/_c_split/
_c_concat) [UNVERIFIED — empty reference mount].

TPU-native: instead of explicit c_allreduce/c_allgather calls, weights are
*placed* with NamedSharding over the 'mp' mesh axis and XLA's sharding
propagation inserts the collectives (SURVEY.md §2.3 mapping).  Column →
weight sharded on out-features; Row → sharded on in-features with the
product reduced over 'mp' (XLA emits the allreduce the reference codes by
hand).  Works identically in eager (global arrays) and under
to_static/pjit.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .....nn import Layer, functional as F
from .....nn import initializer as I
from ....env import global_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_axis(mesh):
    for cand in ("mp", "tp", "model"):
        if cand in mesh.axis_names:
            return cand
    return None


def _place(param, spec_entries):
    """Attach a NamedSharding to a parameter (dist placement)."""
    mesh = global_mesh()
    axis = _mp_axis(mesh)
    if axis is None:
        return
    entries = [axis if e == "MP" else None for e in spec_entries]
    sharding = NamedSharding(mesh, P(*entries))
    param.dist_spec = sharding
    param.is_distributed = True
    try:
        param._value = jax.device_put(param._value, sharding)
    except Exception:
        pass  # mesh larger than hardware (unit tests)


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        _place(self.weight, ["MP", None])  # vocab dim sharded

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, [None, "MP"])  # out-features sharded
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
            _place(self.bias, ["MP"])
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _with_sharding_constraint(out, None)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, ["MP", None])  # in-features sharded
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        # contraction over the sharded dim → XLA inserts the allreduce the
        # reference's _mp_allreduce performs explicitly
        out = F.linear(x, self.weight, self.bias)
        out = _with_sharding_constraint(out, None)
        return out


def _with_sharding_constraint(t, entry):
    """Constrain a tensor's sharding (replicated when entry is None)."""
    from ..pp_utils.global_schedule import constraints_suspended
    if constraints_suspended():
        # inside the pipeline engine's stage-vmap the activation carries
        # a pp-sharded stage dim these specs don't know about
        return t
    mesh = global_mesh()
    axis = _mp_axis(mesh)
    if axis is None:
        return t
    from .....core.dispatch import dispatch

    spec = P() if entry is None else P(*entry)

    def impl(v, *, spec):
        try:
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec))
        except Exception:
            return v

    return dispatch("sharding_constraint", impl, (t,), dict(spec=spec))


def _axis_in_scope(axis_name):
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, Exception):
        return False


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy.

    Reference parity: `c_softmax_with_cross_entropy` op — each mp rank
    holds a vocab shard; max/sum reduce over the mp group.

    Two execution contexts:
      * global sharded arrays (pjit/eager): the class-dim reductions in
        ordinary cross_entropy span the whole array, so XLA lowers them
        to exactly the mp-group collectives — no extra code;
      * inside shard_map (logits are LOCAL vocab shards): the explicit
        vocab-parallel math — pmax of the local max, psum of the local
        sum-exp, psum-gather of the target logit from whichever shard
        owns it.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self.mp_group = mp_group

    def forward(self, input, label):
        mesh = global_mesh()
        axis = (self.mp_group.axis_name if self.mp_group is not None
                else _mp_axis(mesh))
        from .....ops.manipulation import unsqueeze
        if _axis_in_scope(axis):
            loss = self._vocab_parallel_loss(input, label, axis)
            return unsqueeze(loss, -1)
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        return unsqueeze(loss, -1)

    def _vocab_parallel_loss(self, input, label, axis):
        import jax.numpy as jnp
        from .....core.dispatch import dispatch
        ignore = self.ignore_index

        def impl(logits, lab, *, axis, ignore):
            if lab.ndim == logits.ndim and lab.shape[-1] == 1:
                lab = jnp.squeeze(lab, -1)
            v_local = logits.shape[-1]
            offset = jax.lax.axis_index(axis) * v_local
            x = logits.astype(jnp.float32)
            m = jax.lax.pmax(jnp.max(x, axis=-1), axis)
            sumexp = jax.lax.psum(
                jnp.sum(jnp.exp(x - m[..., None]), axis=-1), axis)
            local = lab - offset
            in_shard = jnp.logical_and(local >= 0, local < v_local)
            safe = jnp.clip(local, 0, v_local - 1)
            picked_local = jnp.take_along_axis(
                x, safe[..., None], axis=-1)[..., 0]
            picked = jax.lax.psum(
                jnp.where(in_shard, picked_local, 0.0), axis)
            loss = jnp.log(sumexp) + m - picked
            return jnp.where(lab == ignore, 0.0, loss)

        return dispatch("c_softmax_with_cross_entropy", impl,
                        (input, label), dict(axis=axis, ignore=ignore))
