"""Megatron-style tensor-parallel layers.

Reference parity: `fleet/meta_parallel/parallel_layers/mp_layers.py`
(VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
ParallelCrossEntropy) + `fleet/layers/mpu/mp_ops.py` (_c_identity/_c_split/
_c_concat) [UNVERIFIED — empty reference mount].

TPU-native: instead of explicit c_allreduce/c_allgather calls, weights are
*placed* with NamedSharding over the 'mp' mesh axis and XLA's sharding
propagation inserts the collectives (SURVEY.md §2.3 mapping).  Column →
weight sharded on out-features; Row → sharded on in-features with the
product reduced over 'mp' (XLA emits the allreduce the reference codes by
hand).  Works identically in eager (global arrays) and under
to_static/pjit.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .....nn import Layer, functional as F
from .....nn import initializer as I
from ....env import global_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_axis(mesh):
    for cand in ("mp", "tp", "model"):
        if cand in mesh.axis_names:
            return cand
    return None


def _place(param, spec_entries):
    """Attach a NamedSharding to a parameter (dist placement)."""
    mesh = global_mesh()
    axis = _mp_axis(mesh)
    if axis is None:
        return
    entries = [axis if e == "MP" else None for e in spec_entries]
    sharding = NamedSharding(mesh, P(*entries))
    param.dist_spec = sharding
    param.is_distributed = True
    try:
        param._value = jax.device_put(param._value, sharding)
    except Exception:
        pass  # mesh larger than hardware (unit tests)


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        _place(self.weight, ["MP", None])  # vocab dim sharded

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, [None, "MP"])  # out-features sharded
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
            _place(self.bias, ["MP"])
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _with_sharding_constraint(out, None)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, ["MP", None])  # in-features sharded
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        # contraction over the sharded dim → XLA inserts the allreduce the
        # reference's _mp_allreduce performs explicitly
        out = F.linear(x, self.weight, self.bias)
        out = _with_sharding_constraint(out, None)
        return out


def _with_sharding_constraint(t, entry):
    """Constrain a tensor's sharding (replicated when entry is None)."""
    mesh = global_mesh()
    axis = _mp_axis(mesh)
    if axis is None:
        return t
    from .....core.dispatch import dispatch

    spec = P() if entry is None else P(*entry)

    def impl(v, *, spec):
        try:
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec))
        except Exception:
            return v

    return dispatch("sharding_constraint", impl, (t,), dict(spec=spec))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy.

    Reference parity: `c_softmax_with_cross_entropy` op — each mp rank
    holds a vocab shard; max/sum reduce over the mp group.  Here logits
    arrive sharded on the class dim and XLA's sharded reductions compute
    exactly those collectives.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from .....ops.manipulation import unsqueeze
        return unsqueeze(loss, -1)
