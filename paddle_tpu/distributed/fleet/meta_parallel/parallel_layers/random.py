"""RNG state tracker for model parallel dropout parity.

Reference parity: `fleet/meta_parallel/parallel_layers/random.py`
(RNGStatesTracker: named RNG states; dropout inside TP regions uses
local_seed so each mp rank drops different units, while global state stays
synced) [UNVERIFIED — empty reference mount].

TPU-native: states are PRNG keys derived by fold_in(rank) (SURVEY.md §2.3
mapping: RNGStatesTracker ↔ jax.random.fold_in).
"""
from __future__ import annotations

import contextlib

import jax

from .....framework.random import default_generator, Generator

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "LOCAL_SEED", "GLOBAL_SEED"]

LOCAL_SEED = "local_seed"
GLOBAL_SEED = "global_seed"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        g = Generator(int(seed))
        self.states_[name] = g

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            if n in self.states_:
                self.states_[n].set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name=LOCAL_SEED):
        if name not in self.states_:
            # derive lazily from the default generator
            self.add(name, hash(name) % (2 ** 31))
        import paddle_tpu.framework.random as fr

        g = self.states_[name]
        prev = fr._default_generator
        fr._default_generator = g
        try:
            yield
        finally:
            fr._default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from ....env import get_rank
    from ....fleet import fleet_facade

    hcg = fleet_facade.get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank() if hcg else 0
    seed = seed or pyrandom.randint(0, 2 ** 31)
    global_seed = seed
    local_seed = seed + 1024 + mp_rank
    _tracker.reset()
    _tracker.add(GLOBAL_SEED, global_seed)
    _tracker.add(LOCAL_SEED, local_seed)
    default_generator().manual_seed(global_seed)
