"""Pipeline layer descriptors + PipelineLayer.

Reference parity: `fleet/meta_parallel/parallel_layers/pp_layers.py`
(LayerDesc, SharedLayerDesc, PipelineLayer segmenting by layer count or
parameter count) [UNVERIFIED — empty reference mount].

TPU-native: PipelineLayer builds all stages' layers and records the
stage→segment map.  Stage parameters can be placed on the 'pp' axis of the
mesh (one stage per pp-coordinate); PipelineParallel.train_batch runs the
1F1B microbatch schedule (see pipeline_parallel.py).
"""
from __future__ import annotations

import numpy as np

from .....nn import Layer, LayerList, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({getattr(self.layer_func, '__name__', '?')})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = \
            int(num_virtual_pipeline_stages or 1)
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        descs = list(layers)
        self._shared = {}
        built = []
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad layer desc {d}")
        self.run_function = built
        self._layers_holder = LayerList(
            [l for l, _ in built if isinstance(l, Layer)])
        # stage segmentation (uniform by layer count)
        n = len(built)
        per = -(-n // self._num_stages)
        self._segments = [
            (i * per, min((i + 1) * per, n))
            for i in range(self._num_stages)]

    def get_num_stages(self):
        return self._num_stages

    def segment(self, stage_id):
        lo, hi = self._segments[stage_id]
        return self.run_function[lo:hi]

    def forward(self, x, stage_range=None):
        items = self.run_function if stage_range is None else \
            self.run_function[stage_range[0]:stage_range[1]]
        from ...recompute import recompute as _rc

        for i, (fn, fwd) in enumerate(items):
            call = (lambda t, fn=fn, fwd=fwd:
                    fwd(fn, t) if fwd is not None else fn(t))
            if self._recompute_interval and \
                    i % self._recompute_interval == 0 and \
                    isinstance(x, object):
                x = _rc(call, x)
            else:
                x = call(x)
        return x

    def loss(self, output, label):
        if self._loss_fn is not None:
            return self._loss_fn(output, label)
        return output
