"""TensorParallel wrapper.

Reference parity: `fleet/meta_parallel/tensor_parallel.py` — broadcast
inputs and NON-distributed parameters across the mp group so every mp
rank starts from identical replicated weights [UNVERIFIED — empty
reference mount].

TPU-native: the mp_layers already place their weights on the 'mp' mesh
axis, so the wrapper must (a) NOT clobber those placements when it
replicates everything else (DataParallel's blanket replication would
reshard a ColumnParallelLinear weight back to replicated), and (b) in
multi-process mode align the replicated parameters to mp-rank 0's
values — each process initializes with its own host RNG, which is the
exact divergence the reference's broadcast exists to fix.
"""
from __future__ import annotations

import jax

from ...parallel import DataParallel

__all__ = ["TensorParallel"]


class TensorParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        self._hcg = hcg
        super().__init__(layers)

    def _sync_replicated_params(self, params):
        """Multi-process: align replicated params to process 0's
        values (each process initializes with its own host RNG — the
        divergence the reference's mp-group broadcast exists to fix).
        Uses multihost_utils.broadcast_one_to_all, which really moves
        data (the eager collective API's broadcast is an identity on
        already-replicated arrays)."""
        if jax.process_count() <= 1:
            return
        if self._hcg is not None:
            group = self._hcg.get_model_parallel_group()
            nranks = getattr(group, "nranks", 1) if group else 1
            if nranks > 1 and nranks != jax.process_count():
                import logging
                logging.getLogger("paddle_tpu.distributed").warning(
                    "TensorParallel: mp group (%d ranks) is a strict "
                    "subset of the %d processes; parameter sync "
                    "broadcasts from global process 0 — per-subgroup "
                    "sources are not supported", nranks,
                    jax.process_count())
        from jax.experimental import multihost_utils
        if not params:
            return
        # one pytree collective, not one blocking broadcast per param
        synced = multihost_utils.broadcast_one_to_all(
            [p._value for p in params])
        for p, v in zip(params, synced):
            p._value = jax.device_put(v, p._value.sharding)
