"""TensorParallel wrapper.

Reference parity: `fleet/meta_parallel/tensor_parallel.py` (broadcast
inputs/params across mp group) [UNVERIFIED — empty reference mount].
TPU-native: the mp_layers already placed weights on the 'mp' axis; inputs
stay replicated (XLA broadcasts), so the wrapper only handles dp-axis input
sharding like DataParallel.
"""
from __future__ import annotations

from ...parallel import DataParallel

__all__ = ["TensorParallel"]


class TensorParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
