"""meta_parallel: hybrid-parallel wrappers + parallel layers.

Reference parity: `python/paddle/distributed/fleet/meta_parallel/`
[UNVERIFIED — empty reference mount].
"""
from .parallel_layers.mp_layers import (VocabParallelEmbedding,
                                        ColumnParallelLinear,
                                        RowParallelLinear,
                                        ParallelCrossEntropy)
from .parallel_layers.random import (RNGStatesTracker,
                                     get_rng_state_tracker,
                                     model_parallel_random_seed)
from .parallel_layers.pp_layers import (LayerDesc, SharedLayerDesc,
                                        PipelineLayer)
from .tensor_parallel import TensorParallel
from .pipeline_parallel import PipelineParallel
from .sharding.group_sharded import group_sharded_parallel
from .sharding.group_sharded_stage2 import GroupShardedStage2
from .sharding.group_sharded_stage3 import GroupShardedStage3
from .pipeline_parallel import PipelineParallelWithInterleave
from .context_parallel import (ring_attention, ring_attention_local,
                               ulysses_attention, ulysses_attention_local)
from .expert_parallel import (ExpertParallelEngine, global_scatter_local,
                              global_gather_local, moe_ep_forward_local)
