"""PipelineParallel: microbatched pipeline training.

Reference parity: `fleet/meta_parallel/pipeline_parallel.py`
(PipelineParallel.train_batch 1F1B; interleaved variant;
pp_utils/p2p_communication.py send/recv between stage ranks) [UNVERIFIED —
empty reference mount].

TPU-native (SURVEY.md §2.3 PP row): with a single-controller SPMD runtime
the per-rank P2P send/recv loop becomes a *schedule over the mesh*:
- Stage weights are placed on the 'pp' axis coordinate they belong to.
- train_batch splits the batch into micro-batches and runs
  forward/backward per micro-batch, accumulating grads (GPipe semantics —
  identical loss/grad math to 1F1B; 1F1B's benefit is memory, which
  jax.checkpoint recovers).  Inter-stage activation movement is XLA
  resharding over ICI (the collective_permute the reference codes by
  hand).  A shard_map+ppermute 1F1B kernel is the planned upgrade
  (parallel/pipeline.py).
"""
from __future__ import annotations

import numpy as np

from ....core.tensor import Tensor
from ...parallel import DataParallel

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self._pipeline_layer = layers  # a PipelineLayer

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Split into micro-batches; forward+backward each; one step."""
        from ....ops.manipulation import split

        inputs, labels = data
        n_micro = self.accumulate_steps
        if n_micro > 1 and inputs.shape[0] % n_micro == 0:
            micro_in = split(inputs, n_micro, 0)
            micro_lab = split(labels, n_micro, 0)
        else:
            micro_in, micro_lab = [inputs], [labels]
            n_micro = 1

        total_loss = None
        for mi, ml in zip(micro_in, micro_lab):
            out = self._layers(mi) if not hasattr(
                self._layers, "run_function") else self._layers.forward(mi)
            loss_fn = getattr(self._pipeline_layer, "_loss_fn", None)
            loss = loss_fn(out, ml) if loss_fn is not None else out
            scaled = loss * (1.0 / n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = loss if total_loss is None else total_loss + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss * (1.0 / n_micro)

    def eval_batch(self, data, compute_loss=True):
        from ....core.autograd import no_grad

        inputs, labels = data
        with no_grad():
            out = self._layers.forward(inputs) if hasattr(
                self._layers, "run_function") else self._layers(inputs)
            loss_fn = getattr(self._pipeline_layer, "_loss_fn", None)
            if compute_loss and loss_fn is not None:
                return loss_fn(out, labels)
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    pass
