"""PipelineParallel: microbatched pipeline training over a `pp` mesh axis.

Reference parity: `fleet/meta_parallel/pipeline_parallel.py`
(PipelineParallel.train_batch 1F1B; interleaved variant;
pp_utils/p2p_communication.py send/recv between stage ranks) [UNVERIFIED —
empty reference mount].

TPU-native (SURVEY.md §2.3 PP row, §3.6): the per-rank P2P send/recv loop
becomes ONE compiled SPMD schedule (pp_utils/spmd_schedule.py):
stage-stacked parameters sharded over the `pp` mesh axis, a lax.scan over
GPipe ticks with `ppermute` inter-stage activation transfer, remat around
each stage body, and the optimizer update fused into the same executable.

When the model violates the SPMD formulation's constraints (heterogeneous
stages, fp16 GradScaler, tensor/sep parallel mixed in, no mesh), the
engine build fails and train_batch falls back to microbatch gradient
accumulation — same loss/grad math, no inter-stage parallelism — and says
so once in the log.
"""
from __future__ import annotations

import logging

import numpy as np

from ....core.tensor import Tensor
from ...parallel import DataParallel

logger = logging.getLogger("paddle_tpu.pipeline")

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self._pipeline_layer = layers  # a PipelineLayer
        self._engine = None       # SpmdPipelineEngine | False (fallback)

    def forward(self, *args, **kwargs):
        self._sync_from_engine()  # see the engine-trained weights
        return self._layers(*args, **kwargs)

    # ------------------------------------------------------------------
    def _try_build_engine(self, optimizer):
        if self._engine is not None:
            return
        hcg = self._hcg
        ok = (hcg is not None and getattr(hcg, "mesh", None) is not None
              and hcg.get_pipe_parallel_world_size() > 1
              and hasattr(self._pipeline_layer, "segment"))
        if ok:
            from ....optimizer.optimizer import Optimizer as _OptBase
            if type(optimizer)._pure_update is _OptBase._pure_update:
                logger.warning(
                    "pipeline: %s has no fused static update; falling "
                    "back to gradient accumulation",
                    type(optimizer).__name__)
                self._engine = False
                return
            # primary: global-array engine (heterogeneous stages, pp×mp,
            # GradScaler); secondary: shard_map GPipe (homogeneous, mp=1)
            try:
                from .pp_utils import GlobalPipelineEngine
                n_virtual = getattr(self, "_num_virtual_stages", 1)
                self._engine = GlobalPipelineEngine(
                    self._pipeline_layer, hcg, optimizer,
                    n_micro=max(self.accumulate_steps, 1),
                    remat=True, n_virtual=n_virtual)
                logger.info(
                    "pipeline: global-array GPipe engine over pp=%d, "
                    "%d microbatches, %d virtual stage(s)",
                    hcg.get_pipe_parallel_world_size(),
                    max(self.accumulate_steps, 1), n_virtual)
                return
            except Exception as e:
                logger.warning(
                    "pipeline: global engine unavailable (%s); trying "
                    "the shard_map engine", e)
            try:
                if (hcg.get_model_parallel_world_size() != 1
                        or hcg.get_sep_parallel_world_size() != 1):
                    raise ValueError("shard_map engine requires mp=1 "
                                     "and sep=1")
                from .pp_utils import SpmdPipelineEngine
                self._engine = SpmdPipelineEngine(
                    self._pipeline_layer, hcg, optimizer,
                    n_micro=max(self.accumulate_steps, 1),
                    remat=True)
                logger.info(
                    "pipeline: SPMD GPipe engine over pp=%d mesh axis, "
                    "%d microbatches",
                    hcg.get_pipe_parallel_world_size(),
                    max(self.accumulate_steps, 1))
                return
            except Exception as e:
                logger.warning(
                    "pipeline: SPMD engine unavailable (%s); falling back "
                    "to microbatch gradient accumulation (no inter-stage "
                    "parallelism)", e)
        else:
            logger.warning(
                "pipeline: no usable pp mesh; falling back to microbatch "
                "gradient accumulation")
        self._engine = False

    # ------------------------------------------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Split into micro-batches and run the pipeline schedule."""
        use_scaler = scaler is not None and scaler.is_enable()
        # a scaler can only ride the global engine; once an attempt
        # showed this model builds a non-global engine, stop rebuilding
        # per scaler batch
        if not (use_scaler and self._engine is None
                and getattr(self, "_scaler_incompat", False)):
            self._try_build_engine(optimizer)
        engine = self._engine if self._engine not in (None, False) \
            else None
        if engine is not None and use_scaler and \
                not hasattr(engine, "outer"):
            self._scaler_incompat = True
            if engine._dirty:
                engine.sync_params_to_layers()
            # never retire permanently: a later scaler-free batch can
            # rebuild from the (current) eager params
            logger.warning(
                "pipeline: %s cannot serve a GradScaler; this batch "
                "runs on the accumulation path",
                type(engine).__name__)
            self._engine = None
            engine = None
        if engine is not None:
            inputs = data[0]
            n0 = (inputs.shape[0] if hasattr(inputs, "shape")
                  else len(inputs))
            if n0 % engine.n_micro == 0:
                return self._train_batch_spmd(data, optimizer,
                                              lr_scheduler, scaler)
            # ragged batch: the accumulation path trains the EAGER
            # params, so the engine's stacked copies must sync down and
            # the engine rebuilds later from the updated weights
            logger.warning(
                "pipeline: batch %d not divisible by accumulate_steps "
                "%d; running this batch on the accumulation path",
                n0, engine.n_micro)
            if engine._dirty:
                engine.sync_params_to_layers()
            self._engine = None
        return self._train_batch_accum(data, optimizer, lr_scheduler,
                                       scaler)

    def _train_batch_spmd(self, data, optimizer, lr_scheduler,
                          scaler=None):
        import jax.numpy as jnp

        inputs, labels = data
        x = inputs._value if isinstance(inputs, Tensor) else \
            jnp.asarray(np.asarray(inputs))
        y = labels._value if isinstance(labels, Tensor) else \
            jnp.asarray(np.asarray(labels))
        n_micro = self._engine.n_micro
        if x.shape[0] % n_micro != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by accumulate_steps "
                f"{n_micro}")
        xm = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
        ym = y.reshape((n_micro, y.shape[0] // n_micro) + y.shape[1:])
        lr = optimizer.get_lr() if hasattr(optimizer, "get_lr") else 1e-3
        use_scaler = scaler is not None and scaler.is_enable()
        if use_scaler:
            loss, found_inf = self._engine.train_step(
                xm, ym, lr, scale=scaler._scale)
            # in-graph check_finite_and_unscale already gated the fused
            # update; the host just evolves the dynamic scale
            scaler._found_inf = found_inf
            scaler.update()
        else:
            loss = self._engine.train_step(xm, ym, lr)
            if isinstance(loss, tuple):
                loss = loss[0]
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(jnp.asarray(loss, jnp.float32), _internal=True,
                      stop_gradient=True)

    def _train_batch_accum(self, data, optimizer, lr_scheduler=None,
                           scaler=None):
        from ....ops.manipulation import split

        # if the SPMD engine trained first, its stacked params are newer
        self._sync_from_engine()

        inputs, labels = data
        n_micro = self.accumulate_steps
        if n_micro > 1 and inputs.shape[0] % n_micro == 0:
            micro_in = split(inputs, n_micro, 0)
            micro_lab = split(labels, n_micro, 0)
        else:
            micro_in, micro_lab = [inputs], [labels]
            n_micro = 1

        total_loss = None
        for mi, ml in zip(micro_in, micro_lab):
            out = self._layers(mi) if not hasattr(
                self._layers, "run_function") else self._layers.forward(mi)
            loss_fn = getattr(self._pipeline_layer, "_loss_fn", None)
            loss = loss_fn(out, ml) if loss_fn is not None else out
            scaled = loss * (1.0 / n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = loss if total_loss is None else total_loss + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss * (1.0 / n_micro)

    # ------------------------------------------------------------------
    def _sync_from_engine(self):
        if self._engine not in (None, False):
            self._engine.sync_params_to_layers()

    def eval_batch(self, data, compute_loss=True):
        from ....core.autograd import no_grad

        self._sync_from_engine()
        inputs, labels = data
        with no_grad():
            out = self._layers.forward(inputs) if hasattr(
                self._layers, "run_function") else self._layers(inputs)
            loss_fn = getattr(self._pipeline_layer, "_loss_fn", None)
            if compute_loss and loss_fn is not None:
                return loss_fn(out, labels)
        return out

    def state_dict(self, *args, **kwargs):
        self._sync_from_engine()
        return super().state_dict(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual-pipeline) variant.

    Reference parity: `fleet/meta_parallel/pipeline_parallel.py`
    PipelineParallelWithInterleave (Megatron virtual stages)
    [UNVERIFIED — empty reference mount; SURVEY.md:156].

    TPU-native redesign: the trunk is cut into pp*v chunks assigned
    ROUND-ROBIN (chunk c -> mesh slot c % pp, phase c // pp) and the
    global-array engine's scan computes ONE chunk per slot per tick —
    each slot's active chunk is selected by a per-(tick, slot) phase
    index that GATHERS the chunk's weights from a replicated (v, ...)
    dim of the pp-sharded parameter stack.  Selection over weights is
    data movement, not a serial loop over v chunks (and not a
    lax.switch, which under vmap would execute every branch), so a
    tick costs ~1/v of a full-stage tick and the schedule runs
    n_micro*v + pp - 1 ticks: the fill/drain bubble shrinks from
    (pp-1) full-stage ticks to (pp-1) chunk ticks — the Megatron
    bubble reduction, inside one compiled SPMD program.  See
    GlobalPipelineEngine(n_virtual=v) and PP_MEMORY.md for the
    measured bubble/memory table.
    """

    def __init__(self, layers, hcg=None, strategy=None,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__(layers, hcg=hcg, strategy=strategy, **kwargs)
        # kwarg wins; else the PipelineLayer's own recorded request
        # (constructing this class directly must not silently drop the
        # layer's num_virtual_pipeline_stages)
        self._num_virtual_stages = int(
            num_virtual_pipeline_stages
            or getattr(layers, "_num_virtual_pipeline_stages", 1) or 1)
