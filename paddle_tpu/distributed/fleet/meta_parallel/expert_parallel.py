"""Expert parallelism: all_to_all token dispatch over an `ep` mesh axis.

Reference parity: `fluid/operators/collective/global_scatter_op` /
`global_gather_op` (the MoE all-to-alls) and the EP path of
`incubate/distributed/models/moe/moe_layer.py` [UNVERIFIED — empty
reference mount; SURVEY.md §2.3 EP row].

TPU-native: the reference's global_scatter ships each token's bytes to
the rank owning its expert through NCCL all-to-all.  Here experts live
as a leading dim of STACKED parameter arrays sharded over the `ep` mesh
axis, and inside shard_map one `jax.lax.all_to_all` regroups the
capacity-dispatched slot tensor [E, C, D] from token-major to
expert-major across devices (and back for combine).  Tokens shard over
EVERY mesh axis (dp x ep both carry tokens — the standard EP grid);
expert FFNs run vmapped over the local experts so each expert's matmul
is one batched MXU op.

Functions:
  * global_scatter_local / global_gather_local — the all-to-all
    regroupings, callable inside shard_map (the c_op equivalents);
  * moe_ep_forward_local — full MoE forward on local token shards;
  * ExpertParallelEngine — pure SPMD executor for an eager MoELayer:
    parameters are passed per call (stacked in-graph), so the eager
    tape / jax.grad differentiate straight through and the expert
    Layers stay the single source of truth for weights.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...env import global_mesh
from ...jax_compat import shard_map as _shard_map

__all__ = ["global_scatter_local", "global_gather_local",
           "moe_ep_forward_local", "ExpertParallelEngine"]


def _a2a(x, *, axis, axis_size, mode):
    """Leading-dim all-to-all: the fused collective, or (overlap mode)
    the bit-exact per-peer ppermute ring whose hops XLA can schedule
    under the surrounding expert compute (PR 11 ring discipline)."""
    if mode == "overlap":
        from ...auto_parallel.moe_dispatch import ring_all_to_all_local
        return ring_all_to_all_local(x, axis=axis, axis_size=axis_size,
                                     mode=mode)
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=False)


def global_scatter_local(dispatched, *, axis="ep", axis_size,
                         mode="sequential"):
    """[E, C, D] token-major slots → [E_local, P*C, D] expert-major.

    Chunk p (experts owned by device p) is sent to device p; received
    chunks stack on the slot dim (the reference's global_scatter)."""
    E, C, D = dispatched.shape
    e_loc = E // axis_size
    x = dispatched.reshape(axis_size, e_loc, C, D)
    x = _a2a(x, axis=axis, axis_size=axis_size,
             mode=mode)                          # dim0 now = source dev
    x = jnp.swapaxes(x, 0, 1)                    # [E_loc, P, C, D]
    return x.reshape(e_loc, axis_size * C, D)


def global_gather_local(expert_out, *, axis="ep", axis_size,
                        mode="sequential"):
    """Inverse of global_scatter_local: [E_local, P*C, D] → [E, C, D]."""
    e_loc, PC, D = expert_out.shape
    C = PC // axis_size
    x = expert_out.reshape(e_loc, axis_size, C, D)
    x = jnp.swapaxes(x, 0, 1)                    # [P, E_loc, C, D]
    x = _a2a(x, axis=axis, axis_size=axis_size, mode=mode)
    return x.reshape(axis_size * e_loc, C, D)


def moe_ep_forward_local(x, gating, expert_params, expert_apply,
                         dispatch_fn, *, capacity, axis="ep", axis_size,
                         mode="sequential"):
    """MoE forward on a LOCAL token shard inside shard_map.

    x: [n_local, D] tokens.  gating: (probs, topk_idx, topk_val) local
    slices (the gate itself runs globally OUTSIDE shard_map so the
    load-balancing aux loss sees the global token distribution, exactly
    like the dense layer).  expert_params: pytree with local-expert
    leading dim [E_loc, ...].  expert_apply(params_e, tokens) applies
    ONE expert.  dispatch_fn builds the (dispatched [E, C, D], combine
    [n, E, C]) pair (the GShard capacity routing shared with the dense
    MoELayer).  Returns y [n_local, D]."""
    probs, topk_idx, topk_val = gating
    dispatched, combine = dispatch_fn(x, probs, topk_idx, topk_val,
                                      capacity)
    slots = global_scatter_local(dispatched, axis=axis,
                                 axis_size=axis_size,
                                 mode=mode)             # [E_loc, P*C, D]
    out = jax.vmap(expert_apply)(expert_params, slots)
    gathered = global_gather_local(out, axis=axis, axis_size=axis_size,
                                   mode=mode)            # [E, C, D]
    y = jnp.einsum("nec,ecd->nd", combine.astype(jnp.float32),
                   gathered.astype(jnp.float32)).astype(x.dtype)
    return y


class ExpertParallelEngine:
    """Pure SPMD EP executor for an eager MoELayer.

    __call__(x_val, expert_vals, gate_vals, capacity) is a pure function
    of its inputs (differentiable; callable eagerly or under jit):
    expert_vals are the E experts' parameter arrays in expert-major
    order, stacked in-graph onto the ep-sharded expert dim.
    """

    def __init__(self, moe_layer, mesh=None, axis="ep"):
        from .pp_utils.spmd_schedule import _FunctionalSegment
        self.mesh = mesh or global_mesh()
        if self.mesh is None or axis not in self.mesh.axis_names:
            raise ValueError(f"no '{axis}' axis in mesh")
        self.axis = axis
        self.axis_size = int(self.mesh.shape[axis])
        self.moe = moe_layer
        experts = list(moe_layer.experts)
        self.n_experts = len(experts)
        if self.n_experts % self.axis_size:
            raise ValueError(
                f"{self.n_experts} experts not divisible by "
                f"ep={self.axis_size}")
        sigs = {tuple((tuple(p.shape), str(p.dtype))
                      for p in e.parameters()) for e in experts}
        if len(sigs) != 1:
            raise ValueError("EP requires homogeneous experts")
        self._seg = _FunctionalSegment([(experts[0], None)])
        self._gate_seg = _FunctionalSegment([(moe_layer.gate, None)])
        self.n_p = len(self._seg.params)
        self.expert_tensors = [p for e in experts for p in e.parameters()]
        self.gate_tensors = list(self._gate_seg.params)
        self.tok_axes = tuple(self.mesh.axis_names)

    # -- pure pieces -----------------------------------------------------
    def _gate_fn(self, xv, gate_vals):
        from ....core.autograd import no_grad
        from ....core.tensor import Tensor as T
        gate_layer = self._gate_seg.segment[0][0]
        saved = [(p, p._value) for p in self._gate_seg.params]
        try:
            for p, v in zip(self._gate_seg.params, gate_vals):
                p._value = v
            with no_grad():
                r = gate_layer(T(xv, _internal=True, stop_gradient=True))
            return tuple(t._value if isinstance(t, T) else t for t in r)
        finally:
            for p, v in saved:
                p._value = v

    def __call__(self, x_val, expert_vals, gate_vals, capacity):
        """x_val: global [N, D]; expert_vals: flat tuple of E*n_p arrays
        (expert-major); gate_vals: gate param arrays.
        Returns (y [N, D], aux)."""
        from ....incubate.distributed.models.moe.moe_layer import \
            _dispatch_combine
        axis, axis_size, n_p = self.axis, self.axis_size, self.n_p
        E = self.n_experts
        mesh = self.mesh

        # stack expert params in-graph: [E, ...] sharded over ep
        stacked = []
        for i in range(n_p):
            arr = jnp.stack([expert_vals[e * n_p + i] for e in range(E)])
            spec = P(axis, *([None] * (arr.ndim - 1)))
            try:
                arr = jax.lax.with_sharding_constraint(
                    arr, NamedSharding(mesh, spec))
            except Exception:
                pass  # eager on an un-committed value: advisory only
            stacked.append(arr)

        # gate runs globally (aux loss must see the global distribution)
        probs, topk_idx, topk_val, aux = self._gate_fn(x_val, gate_vals)

        # ep all-to-alls ride the ring-overlap machinery when the active
        # plan's probe admits it (PADDLE_TPU_OVERLAP discipline)
        from ...auto_parallel import overlap as _overlap
        from ...auto_parallel import sharding as _spmd
        a2a_mode = _overlap.select_mode(_spmd.get_mesh_plan(), axis)

        def device_fn(stacked, xl, pl, il, vl):
            return moe_ep_forward_local(
                xl, (pl, il, vl),
                list(stacked),
                lambda pv, t: self._seg(list(pv), t),
                lambda *a: _dispatch_combine(*a),
                capacity=capacity, axis=axis, axis_size=axis_size,
                mode=a2a_mode)

        tok_spec = P(self.tok_axes)
        p_specs = tuple(P(axis, *([None] * (a.ndim - 1)))
                        for a in stacked)
        fn = _shard_map(
            device_fn, mesh=mesh,
            in_specs=(p_specs, tok_spec, tok_spec, tok_spec, tok_spec),
            out_specs=tok_spec)
        y = fn(tuple(stacked), x_val, probs, topk_idx, topk_val)
        return y, aux
