"""Elastic training: heartbeats, failure detection, relaunch.

Reference parity: `python/paddle/distributed/fleet/elastic/manager.py`
(ElasticManager: each rank heartbeats into etcd, the manager watches
membership and on node loss kills local workers and relaunches with
renumbered ranks, resuming from user checkpoints) [UNVERIFIED — empty
reference mount; SURVEY.md §5 "Failure detection / elastic"].

TPU-native: pod slices fail all-or-nothing and there is no etcd — the
health signals are (a) worker process exit, watched by the launch CLI,
and (b) heartbeat staleness in a small KV store: the
jax.distributed coordination service's key-value store when the
multi-controller runtime is up (the same service that replaced
TCPStore), else a shared-filesystem directory (single host / tests).
Recovery is the checkpoint-restore loop: the launcher's
--max_restarts relaunches the pod and training scripts resume from
their latest checkpoint (`paddle.distributed.checkpoint` reshards on
load if the topology changed).
"""
from __future__ import annotations

import json
import os
import threading
import time

from ...fault_tolerance.plan import fault_point, InjectedFault
from ...fault_tolerance.atomic import (validate_checkpoint,
                                       latest_good_checkpoint)

__all__ = ["ElasticStore", "ElasticManager"]


class ElasticStore:
    """Tiny KV for heartbeats, by preference order:
    1. an explicit TCPStore (`PADDLE_ELASTIC_STORE=host:port` → the
       native C++ rendezvous server, distributed/store.py) — the
       closest analog of the reference's etcd registry;
    2. the jax.distributed coordination service when initialized;
    3. a shared directory (single-host fallback)."""

    def __init__(self, path=None):
        self._client = None
        self._tcp = None
        ep = os.environ.get("PADDLE_ELASTIC_STORE")
        if ep and ":" in ep:
            try:
                from ...store import TCPStore
                host, port = ep.rsplit(":", 1)
                self._tcp = TCPStore(host, int(port), is_master=False,
                                     timeout=10)
            except Exception:
                self._tcp = None
        if self._tcp is None:
            try:
                from jax._src import distributed as _dist
                if _dist.global_state.client is not None:
                    self._client = _dist.global_state.client
            except Exception:
                pass
        self._dir = path or os.environ.get(
            "PADDLE_ELASTIC_DIR", "/tmp/paddle_tpu_elastic")
        if self._client is None and self._tcp is None:
            os.makedirs(self._dir, exist_ok=True)

    def set(self, key, value: str):
        if self._tcp is not None:
            self._tcp.set(f"elastic/{key}", value.encode())
            return
        if self._client is not None:
            self._client.key_value_set(f"elastic/{key}", value)
            return
        # atomic replace: a watcher must never read a truncated beat
        p = os.path.join(self._dir, key)
        tmp = f"{p}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, p)

    def get(self, key, default=None):
        if self._tcp is not None:
            v = self._tcp.query(f"elastic/{key}")
            return default if v is None else v.decode()
        if self._client is not None:
            try:
                return self._client.blocking_key_value_get(
                    f"elastic/{key}", 100)
            except Exception:
                return default
        p = os.path.join(self._dir, key)
        if not os.path.exists(p):
            return default
        with open(p) as f:
            return f.read()


class ElasticManager:
    """Heartbeat writer + staleness watchdog.

    Each rank calls start(); the rank-0 watcher (or the launcher)
    polls dead_ranks() and triggers the relaunch path when a rank goes
    silent past the timeout (the reference's etcd-watch equivalent).

    Staleness is judged on the WATCHER's ``time.monotonic()`` clock: a
    beat carries a per-rank sequence number and the watcher tracks how
    long (monotonic) the observed value has gone unchanged.  Comparing
    the writer's wall clock against the watcher's (the old scheme) let
    an NTP step / wall-clock jump on either host fabricate or mask a
    failure; cross-process monotonic clocks aren't comparable, but
    *change detection* against a local monotonic reference is immune to
    both skew and jumps.
    """

    def __init__(self, rank=None, world_size=None, timeout=30.0,
                 interval=3.0, store=None):
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world = world_size if world_size is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.timeout = float(
            os.environ.get("PADDLE_ELASTIC_TIMEOUT", timeout))
        self.interval = interval
        self.store = store or ElasticStore()
        self._stop = threading.Event()
        self._thread = None
        self._seq = 0
        # rank -> (last raw beat value, monotonic time it last changed)
        self._seen = {}

    # ---- heartbeat side ----
    def start(self):
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        try:
            # FaultPlan site: "drop" silences this rank (the watcher
            # must notice), "delay"/"stall" simulates a straggler
            fault_point("heartbeat.beat")
        except InjectedFault:
            return
        self._seq += 1
        self.store.set(f"hb_{self.rank}",
                       f"{self._seq}:{time.time()!r}")

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)

    # ---- watcher side ----
    def last_beat(self, rank):
        """Wall-clock time of the rank's last beat (diagnostics only —
        liveness decisions use monotonic change detection)."""
        v = self.store.get(f"hb_{rank}")
        if not v:
            return None
        _, _, wall = v.partition(":")
        return float(wall or v)

    def dead_ranks(self):
        now = time.monotonic()
        dead = []
        for r in range(self.world):
            raw = self.store.get(f"hb_{r}")
            if raw is None:
                dead.append(r)  # never joined (or key lost)
                continue
            prev = self._seen.get(r)
            if prev is None or prev[0] != raw:
                self._seen[r] = (raw, now)  # fresh beat observed
                continue
            if now - prev[1] > self.timeout:
                dead.append(r)  # value unchanged past the deadline
        return dead

    def healthy(self):
        return not self.dead_ranks()

    # ---- checkpoint auto-resume wiring ----
    # The relaunch path (launch/main.py --max_restarts) restarts the
    # whole pod; workers then ask the elastic registry where to resume.
    # record_checkpoint() is called after a save completes (only valid
    # checkpoints are recorded); resume_checkpoint() re-validates at
    # read time and falls back to the newest good sibling, so a torn
    # write between record and relaunch can't wedge the pod.
    _CKPT_KEY = "ckpt_latest"

    def record_checkpoint(self, path, step=None, validate=True):
        """Publish ``path`` as the resume target (rank 0, post-save).
        Returns False (and records nothing) if validation fails."""
        if validate:
            ok, _ = validate_checkpoint(path)
            if not ok:
                return False
        self.store.set(self._CKPT_KEY,
                       json.dumps({"path": path, "step": step}))
        return True

    def resume_checkpoint(self):
        """(path, step) to resume from, or (None, None).

        The recorded checkpoint is re-validated; on corruption the
        search falls back to the newest valid checkpoint next to it
        (crash-safe saves keep the previous generation intact)."""
        rec = self.store.get(self._CKPT_KEY)
        if rec:
            try:
                d = json.loads(rec)
            except ValueError:
                d = {}
            path = d.get("path")
            if path:
                ok, _ = validate_checkpoint(path)
                if ok:
                    return path, d.get("step")
                fallback = latest_good_checkpoint(
                    os.path.dirname(path.rstrip(os.sep)))
                if fallback:
                    return fallback, None
        return None, None
