"""Elastic training: heartbeats, failure detection, relaunch.

Reference parity: `python/paddle/distributed/fleet/elastic/manager.py`
(ElasticManager: each rank heartbeats into etcd, the manager watches
membership and on node loss kills local workers and relaunches with
renumbered ranks, resuming from user checkpoints) [UNVERIFIED — empty
reference mount; SURVEY.md §5 "Failure detection / elastic"].

TPU-native: pod slices fail all-or-nothing and there is no etcd — the
health signals are (a) worker process exit, watched by the launch CLI,
and (b) heartbeat staleness in a small KV store: the
jax.distributed coordination service's key-value store when the
multi-controller runtime is up (the same service that replaced
TCPStore), else a shared-filesystem directory (single host / tests).
Recovery is the checkpoint-restore loop: the launcher's
--max_restarts relaunches the pod and training scripts resume from
their latest checkpoint (`paddle.distributed.checkpoint` reshards on
load if the topology changed).
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["ElasticStore", "ElasticManager"]


class ElasticStore:
    """Tiny KV for heartbeats, by preference order:
    1. an explicit TCPStore (`PADDLE_ELASTIC_STORE=host:port` → the
       native C++ rendezvous server, distributed/store.py) — the
       closest analog of the reference's etcd registry;
    2. the jax.distributed coordination service when initialized;
    3. a shared directory (single-host fallback)."""

    def __init__(self, path=None):
        self._client = None
        self._tcp = None
        ep = os.environ.get("PADDLE_ELASTIC_STORE")
        if ep and ":" in ep:
            try:
                from ...store import TCPStore
                host, port = ep.rsplit(":", 1)
                self._tcp = TCPStore(host, int(port), is_master=False,
                                     timeout=10)
            except Exception:
                self._tcp = None
        if self._tcp is None:
            try:
                from jax._src import distributed as _dist
                if _dist.global_state.client is not None:
                    self._client = _dist.global_state.client
            except Exception:
                pass
        self._dir = path or os.environ.get(
            "PADDLE_ELASTIC_DIR", "/tmp/paddle_tpu_elastic")
        if self._client is None and self._tcp is None:
            os.makedirs(self._dir, exist_ok=True)

    def set(self, key, value: str):
        if self._tcp is not None:
            self._tcp.set(f"elastic/{key}", value.encode())
            return
        if self._client is not None:
            self._client.key_value_set(f"elastic/{key}", value)
            return
        # atomic replace: a watcher must never read a truncated beat
        p = os.path.join(self._dir, key)
        tmp = f"{p}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, p)

    def get(self, key, default=None):
        if self._tcp is not None:
            v = self._tcp.query(f"elastic/{key}")
            return default if v is None else v.decode()
        if self._client is not None:
            try:
                return self._client.blocking_key_value_get(
                    f"elastic/{key}", 100)
            except Exception:
                return default
        p = os.path.join(self._dir, key)
        if not os.path.exists(p):
            return default
        with open(p) as f:
            return f.read()


class ElasticManager:
    """Heartbeat writer + staleness watchdog.

    Each rank calls start(); the rank-0 watcher (or the launcher)
    polls dead_ranks() and triggers the relaunch path when a rank goes
    silent past the timeout (the reference's etcd-watch equivalent).
    """

    def __init__(self, rank=None, world_size=None, timeout=30.0,
                 interval=3.0, store=None):
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world = world_size if world_size is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.timeout = float(
            os.environ.get("PADDLE_ELASTIC_TIMEOUT", timeout))
        self.interval = interval
        self.store = store or ElasticStore()
        self._stop = threading.Event()
        self._thread = None

    # ---- heartbeat side ----
    def start(self):
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self.store.set(f"hb_{self.rank}", repr(time.time()))

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)

    # ---- watcher side ----
    def last_beat(self, rank):
        v = self.store.get(f"hb_{rank}")
        return float(v) if v else None

    def dead_ranks(self):
        now = time.time()
        dead = []
        for r in range(self.world):
            t = self.last_beat(r)
            if t is None or now - t > self.timeout:
                dead.append(r)
        return dead

    def healthy(self):
        return not self.dead_ranks()
