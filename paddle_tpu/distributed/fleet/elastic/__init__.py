from .manager import ElasticManager, ElasticStore

__all__ = ["ElasticManager", "ElasticStore"]
