"""Meta-optimizers: strategy-driven optimizer wrappers.

Reference parity: `python/paddle/distributed/fleet/meta_optimizers/`
(gradient_merge_optimizer.py, lamb_optimizer.py, ... — static-graph
program rewrites keyed off DistributedStrategy flags) [UNVERIFIED —
empty reference mount; SURVEY.md §2.3 "Static meta-optimizers"].

TPU-native: there is no ProgramDesc to rewrite — both engines bottom
out in the optimizer's fused `_pure_update`, so a meta-optimizer is an
optimizer WRAPPER whose `_pure_update` transforms the inner one and
whose eager `step()` does the same imperative transform.  XLA compiles
the k-step accumulate + conditional apply into the train step (the
reference inserts gradient-merge ops into the program).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer

__all__ = ["GradientMergeOptimizer", "LambOptimizer",
           "ShardingOptimizer", "DGCOptimizer", "LocalSGDOptimizer",
           "FP16AllReduceOptimizer", "apply_meta_optimizers"]


class _InnerDelegate(Optimizer):
    """Wrapper base: __getattr__ covers attribute reads, but methods
    DEFINED on Optimizer (set_lr, state_dict, ...) resolve on the
    wrapper class and would mutate the wrapper's __dict__ instead of
    the wrapped optimizer — silent no-ops.  Forward the mutator/state
    surface explicitly."""

    inner: Optimizer

    def get_lr(self):
        return self.inner.get_lr()

    def set_lr(self, value):
        return self.inner.set_lr(value)

    def set_lr_scheduler(self, scheduler):
        return self.inner.set_lr_scheduler(scheduler)

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, state_dict):
        return self.inner.set_state_dict(state_dict)



class GradientMergeOptimizer(_InnerDelegate):
    """Accumulate grads for k steps, then apply the inner optimizer.

    Works on both engines: eager `step()` accumulates into host-side
    buffers and applies the inner optimizer every k-th call; the static
    `_pure_update` carries the accumulators in opt state and applies
    under `lax.cond` — compiled into the single train-step executable.
    """

    def __init__(self, inner, k_steps=1, avg=True):
        self.inner = inner
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        self._accum = {}
        self._count = 0

    # delegate the Optimizer surface to the inner optimizer
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ---- eager engine ----
    def step(self):
        from ....core.tensor import Tensor
        params = [p for p in self.inner._parameter_list
                  if p.grad is not None]
        for p in params:
            a = self._accum.get(id(p))
            g = p.grad._value
            self._accum[id(p)] = g if a is None else a + g
        self._count += 1
        if self._count % self.k_steps:
            return
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for p in params:
            p.grad._value = self._accum.pop(id(p)) * scale
        self.inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    # ---- static/compiled engines ----
    def _ensure_static_state(self, params):
        inner_state = self.inner._ensure_static_state(params)
        from ....core.tensor import Tensor
        # microstep counter rides in opt state so it is TRACED: the
        # executor compiles the step once, and a python-side counter
        # would bake "(step+1) % k" to a constant
        counter = Tensor(jnp.zeros((), jnp.int64), _internal=True,
                         stop_gradient=True)
        accum = [Tensor(jnp.zeros(p._value.shape, jnp.float32),
                        _internal=True, stop_gradient=True)
                 for p in params]
        return [counter] + accum + list(inner_state)

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        import numpy as np
        if lr is None:
            lr = self.inner._lr_tensor._value
        if step is None:
            step = self.inner._step_count._value
            # numpy, not jnp: this runs during trace and a jnp op would
            # leak a tracer into the eager counter (see
            # Optimizer._static_update)
            self.inner._step_count._inplace_update(np.asarray(step) + 1)
        # `step` itself is unused by _pure_update (the traced microstep
        # counter lives in opt state), but forward it for parity
        return self._pure_update(lr, step, param_vals, grads, opt_vals,
                                 params)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        del step  # traced microstep counter lives in opt_vals[0]
        n = len(param_vals)
        counter = opt_vals[0]
        accum = opt_vals[1:n + 1]
        inner_state = tuple(opt_vals[n + 1:])
        k = self.k_steps
        new_accum = tuple(a + g.astype(jnp.float32)
                          for a, g in zip(accum, grads))
        apply_now = (counter + 1) % k == 0
        scale = 1.0 / k if self.avg else 1.0
        # inner step index counts APPLIES, not microsteps
        inner_step = (counter + 1) // k - 1

        def do_apply(_):
            merged = tuple((a * scale).astype(g.dtype)
                           for a, g in zip(new_accum, grads))
            # the inner optimizer's grad_clip applies to the MERGED grad
            # (parity with the eager path, which clips in inner.step())
            merged = self.inner._clip_static_grads(merged)
            new_p, new_inner = self.inner._pure_update(
                lr, inner_step, param_vals, merged, inner_state, params)
            zeros = tuple(jnp.zeros_like(a) for a in new_accum)
            return tuple(new_p), zeros + tuple(new_inner)

        def keep(_):
            return tuple(param_vals), new_accum + inner_state

        new_p, new_opt = jax.lax.cond(apply_now, do_apply, keep,
                                      operand=None)
        return new_p, (counter + 1,) + tuple(new_opt)


class LambOptimizer(Optimizer):
    """strategy.lamb: swap the inner optimizer for Lamb, keeping its lr
    and parameter list (the reference's lamb_optimizer.py replaces the
    Momentum/Adam ops in the program with lamb ops)."""

    def __new__(cls, inner, lamb_weight_decay=0.01,
                exclude_from_weight_decay=()):
        from ....optimizer import Lamb
        exclude = tuple(exclude_from_weight_decay or ())

        def exclude_fn(p):
            name = getattr(p, "name", "") or ""
            return any(e in name for e in exclude)

        return Lamb(learning_rate=inner._learning_rate,
                    lamb_weight_decay=lamb_weight_decay,
                    parameters=inner._parameter_list,
                    grad_clip=inner._grad_clip,
                    exclude_from_weight_decay_fn=exclude_fn
                    if exclude else None)


class ShardingOptimizer(_InnerDelegate):
    """strategy.sharding: ZeRO-style optimizer-state placement.

    The reference's sharding_optimizer.py is a static-program rewrite
    distributing opt states/params across the sharding group.  Here the
    rewrite is a PLACEMENT: accumulator tensors are device_put sharded
    over the mesh's 'sharding' axis (dim 0 when divisible), so the
    compiled train step stores each shard on one device and XLA inserts
    the gather/scatter the program rewrite would have (stage 1/2; for
    stage 3 use sharding.group_sharded_parallel, which also places
    parameters)."""

    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _shard(self, tensors):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ...env import global_mesh
        mesh = global_mesh()
        axis = next((a for a in ("sharding", "fsdp")
                     if a in mesh.axis_names and mesh.shape[a] > 1), None)
        if axis is None:
            return tensors
        for t in tensors:
            entries = [None] * t._value.ndim
            if t._value.ndim and t._value.shape[0] % mesh.shape[axis] == 0:
                entries[0] = axis
            try:
                t._value = jax.device_put(
                    t._value, NamedSharding(mesh, P(*entries)))
            except ValueError:
                pass
        return tensors

    def step(self):
        self.inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    def _ensure_static_state(self, params):
        return self._shard(self.inner._ensure_static_state(params))

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        return self.inner._static_update(param_vals, grads, opt_vals,
                                         params, lr=lr, step=step)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        return self.inner._pure_update(lr, step, param_vals, grads,
                                       opt_vals, params)


class DGCOptimizer(_InnerDelegate):
    """strategy.dgc: Deep Gradient Compression (Lin et al.) — top-k
    gradient sparsification with local residual accumulation.

    Reference parity: `dgc_optimizer.py` + the DGCMomentum op: each
    worker keeps the (1 - sparsity) small gradient entries in a local
    residual and contributes only the top-k entries to the allreduce
    [UNVERIFIED — empty reference mount].  TPU-native: the collective
    itself is XLA's; the wrapper implements the rank-local semantics —
    residual accumulate → top-k mask → masked gradient to the inner
    optimizer — so the communicated tensor is sparse-in-value (zeros
    compress over ICI and the convergence behavior matches DGC).
    """

    def __init__(self, inner, rampup_begin_step=0, sparsity=0.999):
        self.inner = inner
        self.rampup_begin_step = int(rampup_begin_step)
        if isinstance(sparsity, (list, tuple)):
            sparsity = sparsity[-1]
        self.sparsity = float(sparsity)
        self._residual = {}
        self._count = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _compress(self, g, residual):
        u = residual + g.astype(jnp.float32)
        k = max(1, int(round(u.size * (1.0 - self.sparsity))))
        flat = jnp.abs(u).reshape(-1)
        thresh = jax.lax.top_k(flat, k)[0][-1]
        mask = jnp.abs(u) >= thresh
        send = jnp.where(mask, u, 0.0)
        keep = jnp.where(mask, 0.0, u)
        return send.astype(g.dtype), keep

    # ---- eager engine ----
    def step(self):
        params = [p for p in self.inner._parameter_list
                  if p.grad is not None]
        if self._count >= self.rampup_begin_step:
            for p in params:
                r = self._residual.get(id(p))
                if r is None:
                    r = jnp.zeros(p.grad._value.shape, jnp.float32)
                send, keep = self._compress(p.grad._value, r)
                p.grad._value = send
                self._residual[id(p)] = keep
        self._count += 1
        self.inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    # ---- static/compiled engines ----
    def _ensure_static_state(self, params):
        from ....core.tensor import Tensor
        inner_state = self.inner._ensure_static_state(params)
        residual = [Tensor(jnp.zeros(p._value.shape, jnp.float32),
                           _internal=True, stop_gradient=True)
                    for p in params]
        return residual + list(inner_state)

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        import numpy as np
        if lr is None:
            lr = self.inner._lr_tensor._value
        if step is None:
            step = self.inner._step_count._value
            self.inner._step_count._inplace_update(np.asarray(step) + 1)
        return self._pure_update(lr, step, param_vals, grads, opt_vals,
                                 params)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        n = len(param_vals)
        residual = opt_vals[:n]
        inner_state = tuple(opt_vals[n:])
        sends, keeps = [], []
        for g, r in zip(grads, residual):
            ramped = step >= self.rampup_begin_step
            send, keep = self._compress(g, r)
            sends.append(jnp.where(ramped, send, g))
            keeps.append(jnp.where(ramped, keep, r))
        # the inner optimizer's grad_clip applies to the SPARSIFIED grad
        # (parity with the eager path, where inner.step() clips)
        sends = self.inner._clip_static_grads(tuple(sends))
        new_p, new_inner = self.inner._pure_update(
            lr, step, param_vals, tuple(sends), inner_state, params)
        return tuple(new_p), tuple(keeps) + tuple(new_inner)


class LocalSGDOptimizer(_InnerDelegate):
    """strategy.localsgd: step locally, average parameters across the
    data-parallel group every k_steps.

    Reference parity: `localsgd_optimizer.py` inserts the periodic
    c_allreduce(param)/scale program rewrite [UNVERIFIED].  TPU-native:
    under the single-program SPMD engines parameters are replicated and
    gradients are already globally averaged, so the sync is an identity
    — the wrapper's substance is the MULTI-CONTROLLER eager path, where
    each process trains its own replica and `paddle.distributed.
    all_reduce` averages the weights every k-th step (comm every k
    steps instead of every step — localsgd's point).
    """

    def __init__(self, inner, k_steps=1, begin_step=1):
        self.inner = inner
        self.k_steps = max(1, int(k_steps))
        self.begin_step = int(begin_step)
        self._count = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self.inner.step()
        self._count += 1
        if (self._count >= self.begin_step
                and self._count % self.k_steps == 0):
            self._sync_params()

    def _sync_params(self):
        import jax as _jax
        if _jax.process_count() <= 1:
            return  # replicated single-controller: averaging is identity
        # multi-controller: each process holds its own replica — average
        # with a REAL cross-process psum (a host-local eager all_reduce
        # would be an identity no-op, silently skipping the sync)
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(_jax.devices()), ("lsgd",))
        nd = _jax.device_count()
        nl = _jax.local_device_count()
        from ...jax_compat import shard_map as _shard_map
        avg = _jax.jit(_shard_map(
            lambda x: jax.lax.pmean(x, "lsgd"), mesh=mesh,
            in_specs=P("lsgd"), out_specs=P("lsgd")))
        for p in self.inner._parameter_list:
            local = np.broadcast_to(
                np.asarray(p._value)[None],
                (nl,) + tuple(p._value.shape))
            arr = _jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("lsgd")), local,
                (nd,) + tuple(p._value.shape))
            out = avg(arr)
            host = _jax.device_get(
                list(out.addressable_shards)[0].data)[0]
            p._value = jnp.asarray(host, p._value.dtype)

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    # compiled engines: params replicated + grads globally averaged →
    # the periodic average is an identity; delegate untouched
    def _ensure_static_state(self, params):
        return self.inner._ensure_static_state(params)

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        return self.inner._static_update(param_vals, grads, opt_vals,
                                         params, lr=lr, step=step)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        return self.inner._pure_update(lr, step, param_vals, grads,
                                       opt_vals, params)


class FP16AllReduceOptimizer(_InnerDelegate):
    """strategy.fp16_allreduce: halve gradient-communication volume by
    reducing in half precision.

    Reference parity: `fp16_allreduce_optimizer.py` casts grads to fp16
    around the c_allreduce [UNVERIFIED].  TPU-native: the collective is
    XLA-inserted at the gradient's dtype, so communicating in half
    precision = rounding the gradient through fp16 (bf16 on TPU keeps
    the fp32 exponent range — the default here) before the update; XLA
    then moves half-width words over ICI.
    """

    def __init__(self, inner, dtype="bfloat16"):
        self.inner = inner
        self._comm_dtype = jnp.float16 if str(dtype) == "float16" \
            else jnp.bfloat16

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _round(self, g):
        if g.dtype in (jnp.float16, jnp.bfloat16):
            return g
        return g.astype(self._comm_dtype).astype(g.dtype)

    def step(self):
        for p in self.inner._parameter_list:
            if p.grad is not None:
                p.grad._value = self._round(p.grad._value)
        self.inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    def _ensure_static_state(self, params):
        return self.inner._ensure_static_state(params)

    def _static_update(self, param_vals, grads, opt_vals, params,
                       lr=None, step=None):
        grads = tuple(self._round(g) for g in grads)
        return self.inner._static_update(param_vals, grads, opt_vals,
                                         params, lr=lr, step=step)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        grads = tuple(self._round(g) for g in grads)
        return self.inner._pure_update(lr, step, param_vals, grads,
                                       opt_vals, params)


# strategy flags that are execution-mode switches handled elsewhere in
# this framework (hybrid engines, amp module, recompute wrapper, ...)
_HANDLED_ELSEWHERE = {
    "amp", "recompute", "pipeline", "hybrid_configs", "heter_ccl_mode",
    "find_unused_parameters", "fuse_all_reduce_ops",
    "gradient_scale_configs", "tensor_parallel", "without_graph_optimization",
}


def apply_meta_optimizers(optimizer, strategy):
    """Wrap `optimizer` per the DistributedStrategy flags (the
    reference's meta-optimizer selection in fleet.distributed_optimizer).
    Unknown set flags WARN instead of silently doing nothing."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "lamb", False):
        cfg = getattr(strategy, "lamb_configs", {}) or {}
        optimizer = LambOptimizer(
            optimizer,
            lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
            exclude_from_weight_decay=cfg.get(
                "exclude_from_weight_decay", ()))
    if getattr(strategy, "dgc", False):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        optimizer = DGCOptimizer(
            optimizer,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            sparsity=cfg.get("sparsity", [0.999]))
    if getattr(strategy, "fp16_allreduce", False):
        optimizer = FP16AllReduceOptimizer(optimizer)
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 1))
    if getattr(strategy, "gradient_merge", False):
        cfg = getattr(strategy, "gradient_merge_configs", {})
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    if getattr(strategy, "sharding", False):
        optimizer = ShardingOptimizer(optimizer)

    handled = {"lamb", "dgc", "fp16_allreduce", "localsgd",
               "gradient_merge", "sharding"}
    import logging
    for flag in sorted(vars(strategy)):
        if flag.startswith("_") or flag.endswith("_configs"):
            continue
        if flag in handled or flag in _HANDLED_ELSEWHERE:
            continue
        if getattr(strategy, flag, None) is True:
            logging.getLogger("paddle_tpu.fleet").warning(
                "DistributedStrategy.%s is set but has no "
                "meta-optimizer in this framework; ignored", flag)
    return optimizer
