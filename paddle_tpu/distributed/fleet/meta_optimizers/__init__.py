"""Meta-optimizers: strategy-driven optimizer wrappers.

Reference parity: `python/paddle/distributed/fleet/meta_optimizers/`
(gradient_merge_optimizer.py, lamb_optimizer.py, ... — static-graph
program rewrites keyed off DistributedStrategy flags) [UNVERIFIED —
empty reference mount; SURVEY.md §2.3 "Static meta-optimizers"].

TPU-native: there is no ProgramDesc to rewrite — both engines bottom
out in the optimizer's fused `_pure_update`, so a meta-optimizer is an
optimizer WRAPPER whose `_pure_update` transforms the inner one and
whose eager `step()` does the same imperative transform.  XLA compiles
the k-step accumulate + conditional apply into the train step (the
reference inserts gradient-merge ops into the program).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer

__all__ = ["GradientMergeOptimizer", "apply_meta_optimizers"]


class GradientMergeOptimizer(Optimizer):
    """Accumulate grads for k steps, then apply the inner optimizer.

    Works on both engines: eager `step()` accumulates into host-side
    buffers and applies the inner optimizer every k-th call; the static
    `_pure_update` carries the accumulators in opt state and applies
    under `lax.cond` — compiled into the single train-step executable.
    """

    def __init__(self, inner, k_steps=1, avg=True):
        self.inner = inner
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        self._accum = {}
        self._count = 0

    # delegate the Optimizer surface to the inner optimizer
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ---- eager engine ----
    def step(self):
        from ....core.tensor import Tensor
        params = [p for p in self.inner._parameter_list
                  if p.grad is not None]
        for p in params:
            a = self._accum.get(id(p))
            g = p.grad._value
            self._accum[id(p)] = g if a is None else a + g
        self._count += 1
        if self._count % self.k_steps:
            return
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for p in params:
            p.grad._value = self._accum.pop(id(p)) * scale
        self.inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    # ---- static/compiled engines ----
    def _ensure_static_state(self, params):
        inner_state = self.inner._ensure_static_state(params)
        from ....core.tensor import Tensor
        # microstep counter rides in opt state so it is TRACED: the
        # executor compiles the step once, and a python-side counter
        # would bake "(step+1) % k" to a constant
        counter = Tensor(jnp.zeros((), jnp.int64), _internal=True,
                         stop_gradient=True)
        accum = [Tensor(jnp.zeros(p._value.shape, jnp.float32),
                        _internal=True, stop_gradient=True)
                 for p in params]
        return [counter] + accum + list(inner_state)

    def _static_update(self, param_vals, grads, opt_vals, params):
        lr = self.inner._lr_tensor._value
        step = self.inner._step_count._value
        self.inner._step_count._inplace_update(step + 1)
        return self._pure_update(lr, step, param_vals, grads, opt_vals,
                                 params)

    def _pure_update(self, lr, step, param_vals, grads, opt_vals, params):
        del step  # traced microstep counter lives in opt_vals[0]
        n = len(param_vals)
        counter = opt_vals[0]
        accum = opt_vals[1:n + 1]
        inner_state = tuple(opt_vals[n + 1:])
        k = self.k_steps
        new_accum = tuple(a + g.astype(jnp.float32)
                          for a, g in zip(accum, grads))
        apply_now = (counter + 1) % k == 0
        scale = 1.0 / k if self.avg else 1.0
        # inner step index counts APPLIES, not microsteps
        inner_step = (counter + 1) // k - 1

        def do_apply(_):
            merged = tuple((a * scale).astype(g.dtype)
                           for a, g in zip(new_accum, grads))
            # the inner optimizer's grad_clip applies to the MERGED grad
            # (parity with the eager path, which clips in inner.step())
            merged = self.inner._clip_static_grads(merged)
            new_p, new_inner = self.inner._pure_update(
                lr, inner_step, param_vals, merged, inner_state, params)
            zeros = tuple(jnp.zeros_like(a) for a in new_accum)
            return tuple(new_p), zeros + tuple(new_inner)

        def keep(_):
            return tuple(param_vals), new_accum + inner_state

        new_p, new_opt = jax.lax.cond(apply_now, do_apply, keep,
                                      operand=None)
        return new_p, (counter + 1,) + tuple(new_opt)


def apply_meta_optimizers(optimizer, strategy):
    """Wrap `optimizer` per the DistributedStrategy flags (the
    reference's meta-optimizer selection in fleet.distributed_optimizer)."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "gradient_merge", False):
        cfg = getattr(strategy, "gradient_merge_configs", {})
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    return optimizer
