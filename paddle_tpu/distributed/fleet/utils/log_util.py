"""fleet logging (reference: fleet/utils/log_util.py [UNVERIFIED])."""
import logging
import sys

logger = logging.getLogger("paddle_tpu.fleet")
if not logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [fleet] %(message)s"))
    logger.addHandler(h)
logger.setLevel(logging.INFO)


def set_log_level(level):
    logger.setLevel(level)


def get_logger(level=logging.INFO, name="paddle_tpu.fleet"):
    return logger
