"""fleet logging: VLOG-style levels + per-rank log capture.

Reference parity: `fleet/utils/log_util.py` (python logging) and the
C++ glog `VLOG(n)` convention gated by the GLOG_v env var, with the
launch CLI teeing per-rank worker logs [UNVERIFIED — empty reference
mount; SURVEY.md §5 "Metrics/logging/observability"].

TPU-native notes: every paddle_tpu subsystem logs under the
"paddle_tpu.*" namespace (fleet, pipeline, moe, pallas); this module
owns the shared handler.  GLOG_v=N enables vlog(n<=N) verbose traces
exactly like the reference's C++ side; PADDLE_LOG_DIR (set by the
launch CLI) adds a per-rank file handler so multi-process runs keep
separated logs.
"""
import logging
import os
import sys

_root = logging.getLogger("paddle_tpu")
logger = logging.getLogger("paddle_tpu.fleet")

GLOG_V = int(os.environ.get("GLOG_v", "0"))

if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    _root.addHandler(h)
    _root.setLevel(logging.DEBUG if GLOG_V > 0 else logging.INFO)
    log_dir = os.environ.get("PADDLE_LOG_DIR")
    if log_dir:
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        os.makedirs(log_dir, exist_ok=True)
        fh = logging.FileHandler(
            os.path.join(log_dir, f"paddle_tpu.rank{rank}.log"))
        fh.setFormatter(h.formatter)
        _root.addHandler(fh)


def set_log_level(level):
    """Accepts logging levels or a glog-style int verbosity."""
    if isinstance(level, int) and level < 10:
        global GLOG_V
        GLOG_V = level
        _root.setLevel(logging.DEBUG if level > 0 else logging.INFO)
        return
    _root.setLevel(level)


def vlog(level, msg, *args, logger_name="paddle_tpu.fleet"):
    """VLOG(level): emitted only when GLOG_v >= level (reference: glog
    verbose logging gated by the GLOG_v env var)."""
    if GLOG_V >= level:
        logging.getLogger(logger_name).debug("VLOG(%d) " + msg, level,
                                             *args)


def get_logger(level=logging.INFO, name="paddle_tpu.fleet"):
    lg = logging.getLogger(name)
    if GLOG_V == 0:  # verbose mode: never clamp children below root
        lg.setLevel(level)
    return lg
