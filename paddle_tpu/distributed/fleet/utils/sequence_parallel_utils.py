"""Megatron-style sequence parallelism helpers.

Reference parity: `fleet/utils/sequence_parallel_utils.py`
(ColumnSequenceParallelLinear, RowSequenceParallelLinear, AllGatherOp,
ReduceScatterOp, mark_as_sequence_parallel_parameter,
register_sequence_parallel_allreduce_hooks) [UNVERIFIED — empty
reference mount; SURVEY.md §2.3 SP row].

TPU-native: the reference hand-codes allgather-before-column-linear and
reduce-scatter-after-row-linear on the TP group.  Here activations carry
*sharding constraints* on the sequence dim over the `mp` mesh axis and
XLA's SPMD partitioner inserts the all_gather / reduce_scatter over ICI
(SURVEY.md §2.3: "seq-dim sharding in pjit specs; XLA inserts ag/rs").
The layer classes keep the reference's API; AllGatherOp/ReduceScatterOp
are the explicit-constraint primitives, differentiable because a
resharding constraint transposes to the inverse resharding.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn import Layer
from ...env import global_mesh

__all__ = [
    "AllGatherOp", "ReduceScatterOp", "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear", "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ScatterOp", "GatherOp",
]


def _mp_axis(mesh):
    for cand in ("mp", "tp", "model"):
        if cand in mesh.axis_names:
            return cand
    return None


def _constrain(x, spec_entries):
    """Apply a sharding constraint to a Tensor/array; 'MP' entries bind
    to the mp mesh axis.  No-op without a mesh (single-device tests)."""
    mesh = global_mesh()
    if mesh is None:
        return x
    axis = _mp_axis(mesh)
    if axis is None:
        return x
    spec = P(*[axis if e == "MP" else None for e in spec_entries])
    val = x._value if isinstance(x, Tensor) else x
    try:
        out = jax.lax.with_sharding_constraint(
            val, NamedSharding(mesh, spec))
    except Exception:
        return x  # outside jit on an unsharded value: placement advisory
    if isinstance(x, Tensor):
        t = Tensor(out, _internal=True, stop_gradient=x.stop_gradient)
        t._grad_node = x._grad_node
        return t
    return out


def ScatterOp(x, axis=1):
    """Shard the sequence dim over mp (reference: split to the TP group;
    here a reshard constraint XLA lowers to a local slice)."""
    entries = [None] * (x.ndim if hasattr(x, "ndim") else 3)
    entries[axis] = "MP"
    return _constrain(x, entries)


def GatherOp(x, axis=1):
    """Gather the sequence dim from the mp shards (all_gather)."""
    entries = [None] * (x.ndim if hasattr(x, "ndim") else 3)
    return _constrain(x, entries)


AllGatherOp = GatherOp        # reference names both; gather == allgather
ReduceScatterOp = ScatterOp   # partial-sum in → seq-sharded out


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose INPUT is sequence-sharded.

    [B, S/mp, in] --(XLA all_gather over mp)--> [B, S, in] @ W[:, out/mp]
    → [B, S, out/mp].  Weight is placed column-sharded on the mp axis.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            shape=[out_features], attr=None, is_bias=True) \
            if has_bias else None
        self.gather_output = gather_output
        from ..meta_parallel.parallel_layers.mp_layers import _place
        _place(self.weight, (None, "MP"))
        if self.bias is not None:
            _place(self.bias, ("MP",))

    def forward(self, x):
        from ....nn import functional as F
        x = _constrain(x, (None, "MP", None))   # seq-sharded in
        y = F.linear(x, self.weight, self.bias)
        y = _constrain(y, (None, None, None if self.gather_output
                           else "MP"))
        return y


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose OUTPUT is sequence-sharded.

    [B, S, in/mp] @ W[in/mp, out] → partial sums; the output constraint
    [B, S/mp, out] makes XLA emit the reduce_scatter the reference codes
    by hand.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            shape=[out_features], attr=None, is_bias=True) \
            if has_bias else None
        from ..meta_parallel.parallel_layers.mp_layers import _place
        _place(self.weight, ("MP", None))

    def forward(self, x):
        from ....nn import functional as F
        x = _constrain(x, (None, None, "MP"))
        y = F.linear(x, self.weight, None)
        y = _constrain(y, (None, "MP", None))   # seq-sharded out (rs)
        if self.bias is not None:
            y = y + self.bias
        return y


def mark_as_sequence_parallel_parameter(param):
    """Tag a parameter (e.g. LayerNorm weight inside the SP region) so
    its gradient is summed over the mp group."""
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse=False):
    """Reference behavior: backward hooks allreduce marked params' grads
    over the TP group (each rank saw only its sequence shard).

    In this single-controller runtime eager tensors are global values and
    sharded execution happens under pjit, where XLA already reduces the
    gradient of a replicated parameter across the mesh — so there is no
    residual per-rank partial grad to fix up.  The function validates the
    marks and exists for API parity.
    """
    marked = [p for p in model.parameters()
              if getattr(p, "sequence_parallel", False)]
    return marked
