from . import log_util
from ..recompute import recompute, recompute_sequential
