from . import log_util
from ..recompute import recompute, recompute_sequential
from . import sequence_parallel_utils
