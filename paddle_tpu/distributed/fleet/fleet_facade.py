"""fleet facade: init / distributed_model / distributed_optimizer.

Reference parity: `python/paddle/distributed/fleet/fleet.py` [UNVERIFIED —
empty reference mount].
"""
from __future__ import annotations

import os

from ..env import (init_parallel_env, get_rank, get_world_size,
                   global_mesh)
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["init", "is_first_worker", "worker_index", "worker_num",
           "is_worker", "worker_endpoints", "server_num",
           "distributed_model", "distributed_optimizer",
           "get_hybrid_communicate_group", "barrier_worker", "init_worker",
           "stop_worker", "save_persistables"]

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
}


def init(role_maker=None, is_collective=False, strategy=None, log_level=2):
    strategy = strategy or DistributedStrategy()
    _fleet_state["strategy"] = strategy
    init_parallel_env()
    world = get_world_size()
    hc = strategy.hybrid_configs
    mp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sh = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    dp = int(hc.get("dp_degree", -1))
    if dp == -1:
        denom = mp * pp * sh * sep
        dp = max(world // denom, 1)
    topo = CommunicateTopology(
        ("data", "pipe", "sharding", "sep", "model"),
        (dp, pp, sh, sep, mp))
    _fleet_state["hcg"] = HybridCommunicateGroup(topo)
    _fleet_state["initialized"] = True
    return None


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_worker():
    return True


def worker_endpoints(to_string=False):
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:0")
    return eps if to_string else eps.split(",")


def server_num():
    return 0


def barrier_worker():
    from ..communication.ops import barrier
    barrier()


def init_worker():
    pass


def stop_worker():
    pass


def save_persistables(executor, dirname, main_program=None, mode=0):
    pass


def distributed_model(model):
    """Wrap per the hybrid strategy (SURVEY.md §3.4):
       pure DP → DataParallel (mesh-sharded inputs);
       mp/pp → meta_parallel wrappers (params already carry shardings).
    """
    hcg = _fleet_state["hcg"]
    if hcg is None:
        init()
        hcg = _fleet_state["hcg"]
    if hcg.get_pipe_parallel_world_size() > 1:
        n_virtual = getattr(model, "_num_virtual_pipeline_stages", 1)
        if n_virtual > 1:
            from .meta_parallel.pipeline_parallel import \
                PipelineParallelWithInterleave
            return PipelineParallelWithInterleave(
                model, hcg, _fleet_state["strategy"],
                num_virtual_pipeline_stages=n_virtual)
        from .meta_parallel.pipeline_parallel import PipelineParallel
        return PipelineParallel(model, hcg,
                                _fleet_state["strategy"])
    if hcg.get_model_parallel_world_size() > 1:
        from .meta_parallel.tensor_parallel import TensorParallel
        return TensorParallel(model, hcg, _fleet_state["strategy"])
    from ..parallel import DataParallel
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Wrap the optimizer per the DistributedStrategy meta-optimizer
    flags (reference: fleet's meta-optimizer chain); grad SYNC itself is
    XLA's job via sharding, so no HybridParallelOptimizer comm
    scheduling is needed."""
    strategy = strategy or _fleet_state.get("strategy")
    from .meta_optimizers import apply_meta_optimizers
    return apply_meta_optimizers(optimizer, strategy)
