from . import distributed_strategy, topology
from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup
