"""DistributedStrategy.

Reference parity: `python/paddle/distributed/fleet/base/
distributed_strategy.py` wrapping distributed_strategy.proto [UNVERIFIED —
empty reference mount].  Plain-python config object with the same nested
configs (hybrid_configs, sharding_configs, amp_configs, ...).
"""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        # execution modes
        self.auto = False
        self.a_sync = False
        self.a_sync_configs = {}
        # amp
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "custom_white_list": [],
            "custom_black_list": [], "use_pure_fp16": False,
            "use_fp16_guard": True, "use_bf16": False,
        }
        # recompute
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        # gradient merge
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # sharding (ZeRO)
        self.sharding = False
        self.sharding_configs = {
            "sharding_degree": 1, "stage": 1, "offload": False,
            "comm_overlap": True,
        }
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {
            "accumulate_steps": 1, "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        # tensor parallel
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        # hybrid
        self.hybrid_configs = {
            "dp_degree": -1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        # misc meta-optimizers
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.localsgd = False
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.heter_ccl_mode = False
        self.is_fl_ps_mode = False
        self.without_graph_optimization = True
        self.asp = False
        self.qat = False
        self.qat_configs = {}

    def __repr__(self):
        keys = ["amp", "recompute", "sharding", "pipeline",
                "tensor_parallel", "hybrid_configs"]
        return "DistributedStrategy(" + ", ".join(
            f"{k}={getattr(self, k)}" for k in keys) + ")"
