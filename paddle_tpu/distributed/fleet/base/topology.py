"""Hybrid topology: the rank cube → named mesh axes.

Reference parity: `python/paddle/distributed/fleet/base/topology.py`
(CommunicateTopology / HybridCommunicateGroup building dp/mp/pp/sharding/
sep sub-groups from PADDLE env ranks) [UNVERIFIED — empty reference
mount].

TPU-native: the rank cube IS a jax.sharding.Mesh with axes named
(pp, dp, sharding, sep, mp) (reference order [dp, pp, sharding, sep, mp]
reordered so pp is outermost = most DCN-tolerant, mp innermost = fastest
ICI axis, per the scaling-book recipe).  Each "communicate group" is just
a mesh axis name; collectives resolve axes by name inside shard_map.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np
import jax
from jax.sharding import Mesh

from ...env import get_rank, get_world_size, set_global_mesh
from ...communication.group import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            "Coordinate", self._parallel_names)
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in
                      itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        return self._coord2rank[self.coordinate(**kwargs)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank lists."""
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for combo in itertools.product(*[range(self._dims[i])
                                         for i in other]):
            ranks = []
            for k in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, v in zip(other, combo):
                    coord[i] = v
                coord[axis] = k
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        d = coord._asdict()
        d.update(kwargs)
        return self.get_rank(**d)


# jax mesh axis names for each parallel dim
_AXIS_NAME = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = get_rank()
        self.nranks = topology.world_size()
        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1

        # Build the device mesh: order pp (outermost) … mp (innermost).
        names = topology.get_hybrid_group_names()
        mesh_order = [n for n in ("pipe", "data", "sharding", "sep",
                                  "model") if n in names]
        dims = [topology.get_dim(n) for n in mesh_order]
        n_needed = int(np.prod(dims))
        devs = np.asarray(jax.devices())
        if len(devs) >= n_needed:
            devs = devs[:n_needed]
            self._mesh = Mesh(devs.reshape(dims),
                              tuple(_AXIS_NAME[n] for n in mesh_order))
            set_global_mesh(self._mesh)
        else:
            self._mesh = None  # described topology larger than hardware

        coord = topology.get_coord(self.global_rank)
        self._dp_group = self._make_group("data", coord)
        self._mp_group = self._make_group("model", coord)
        self._pp_group = self._make_group("pipe", coord)
        self._sharding_group = self._make_group("sharding", coord)
        self._sep_group = self._make_group("sep", coord) \
            if "sep" in topology.get_hybrid_group_names() else None
        # check-parallel group (dp+sharding combined, for loss checks)
        self._check_group = new_group(list(range(self.nranks)),
                                      axis_name=None)

    def _make_group(self, axis, coord):
        idx = getattr(coord, axis)
        my_lists = self._topo.get_comm_list(axis)
        ranks = next(l for l in my_lists if self.global_rank in l)
        return new_group(ranks, axis_name=_AXIS_NAME[axis])

    @property
    def mesh(self):
        return self._mesh

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # ---- degrees ----
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    # ---- ranks ----
    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def get_data_parallel_rank(self):
        return self._coord().data

    def get_model_parallel_rank(self):
        return self._coord().model

    def get_stage_id(self):
        return self._coord().pipe

    get_pipe_parallel_rank = get_stage_id

    def get_sharding_parallel_rank(self):
        return self._coord().sharding

    def get_sep_parallel_rank(self):
        c = self._coord()
        return getattr(c, "sep", 0)

    # ---- groups ----
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, *args):
        return self._check_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline helpers
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id)
