"""Elastic preemption-tolerant training (detection -> shrink -> resume).

PAPER.md's target is a training run on a *preemptible* v5p pod;
upstream Paddle ships a whole ``fleet/elastic`` tier for the same
reason.  This module closes the training-side loop the serving tier got
in PR 12:

detection
    Health probes at every step boundary: the ``dist.device_lost.<rank>``
    / ``dist.host_preempt`` fault sites, :class:`ElasticManager`
    ``dead_ranks()`` heartbeat staleness, and
    :class:`CollectiveTimeoutError` from the collective watchdog all
    escalate into one structured :class:`DeviceLostError`.  The step
    aborts cleanly: the pipeline ``InFlightWindow`` is drained (no
    leaked in-flight buffers) and the snapshot staging line item is
    released from the memory guard.

mesh-shrink recovery
    :meth:`MeshPlan.shrink` rebuilds the plan over the surviving
    devices — dp drops to the largest divisor that fits (so global
    batch stays divisible and resume is bit-identical), model-parallel
    axes that no longer fit fall back to replication with a TPU505
    finding.  The shrunk plan carries a bumped ``_generation`` inside
    ``cache_token()``, so executor/trace caches compile fresh instead
    of poisoning (or reusing) pre-loss entries.

async snapshot checkpointing
    At a step boundary the trainer captures a device->host copy of the
    training state (params, optimizer accumulators, step counter) —
    charged to the memory guard as a HOST line item — and a background
    thread writes it through the PR 1 tmp+rename+sha256-manifest path.
    The manifest's ``"train"`` block records ``step``, the RNG key, and
    the data-loader cursor.

deterministic resume
    Restore re-places every tensor under the shrunk plan via
    :func:`make_shard_and_gather_fns`, restores the RNG key and step
    counter from the manifest, and resumes the feed callback at the
    recorded cursor — bit-identical to a clean run started from the
    same checkpoint on the shrunk mesh (the chaos drill asserts it).

Observability: ``elastic.restarts`` / ``elastic.lost_steps`` counters,
an ``elastic.mttr_ms`` histogram, and ``recovery`` / ``ckpt`` timeline
lanes folded into ``phase_breakdown()``.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time

import numpy as np

from .. import observability as obs
from ..core import pipeline as _pipeline
from ..memory.guard import register_resident, unregister_resident
from .auto_parallel.sharding import (get_mesh_plan,
                                     make_shard_and_gather_fns,
                                     set_mesh_plan)
from .fault_tolerance.atomic import (MANIFEST_NAME, atomic_write,
                                     validate_checkpoint, write_manifest)
from .fault_tolerance.plan import InjectedFault, fault_point
from .fault_tolerance.watchdog import CollectiveTimeoutError

__all__ = ["DeviceLostError", "ElasticTrainer", "elastic_state_dict",
           "run_elastic_drill"]

_SNAP_PREFIX = "snap_"
_STAGING_ITEM = "elastic.snapshot"


class DeviceLostError(RuntimeError):
    """A device (or the whole host) dropped out of the training mesh.

    ``lost_ranks``: flat mesh indices of the lost devices (empty when
    the whole host was preempted).  ``preempted``: True for a host-level
    preemption notice — recovery restarts on the same topology instead
    of shrinking.
    """

    def __init__(self, lost_ranks, reason="", preempted=False):
        self.lost_ranks = sorted(set(int(r) for r in lost_ranks))
        self.reason = reason or "device lost"
        self.preempted = bool(preempted)
        what = ("host preempted" if preempted
                else f"device(s) lost: ranks {self.lost_ranks}")
        super().__init__(f"{what} ({self.reason})")


def elastic_state_dict(model, optimizer=None):
    """The ``{name: Tensor}`` training state an :class:`ElasticTrainer`
    snapshots: named parameters plus (prefixed) optimizer accumulators
    and the step counter.  Names are stable across a recovery because
    the same live objects are restored in place."""
    from ..core.tensor import Tensor
    state = {}
    for name, p in model.named_parameters():
        state[name] = p
    if optimizer is not None:
        for key, t in optimizer.state_dict().items():
            if isinstance(t, Tensor):
                state[f"opt::{key}"] = t
    return state


def _rng_state_host():
    from ..framework import random as _random
    return np.asarray(_random.default_generator().get_state()._value)


def _set_rng_state_host(key):
    from ..framework import random as _random
    arr = np.asarray(key, dtype=np.uint32)
    _random.default_generator().set_state(arr)


# ---------------------------------------------------------------------------
# Async snapshots
# ---------------------------------------------------------------------------

def _capture_host_state(state_dict):
    """Device->host copy of every tensor (the staging buffer): a
    consistent point-in-time image, synchronizing each fetch."""
    host, meta, nbytes = {}, {}, 0
    for name, t in state_dict.items():
        arr = np.asarray(t._value)
        host[name] = arr
        meta[name] = {"type": "tensor",
                      "global_shape": list(arr.shape),
                      "dtype": arr.dtype.name}
        nbytes += arr.nbytes
    return host, meta, nbytes


def _write_snapshot(path, host, meta, train_meta):
    """Background-thread body: crash-safe snapshot commit through the
    atomic tmp+rename+sha256-manifest path (save_state_dict layout, so
    ``checkpoint.load_state_dict`` can read it too)."""
    os.makedirs(path, exist_ok=True)
    fault_point("elastic.snapshot.write", path=path)
    shards = {name: [{"index": [[0, d] for d in arr.shape],
                      "data": arr}]
              for name, arr in host.items()}
    with atomic_write(os.path.join(path, "shard_0.pkl")) as f:
        pickle.dump(shards, f)
    with atomic_write(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f)
    write_manifest(path, extra={"train": dict(train_meta)})


def read_train_meta(path):
    """The manifest's ``"train"`` block, or ``None``."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f).get("train")
    except (OSError, ValueError):
        return None


def list_snapshots(ckpt_dir):
    """Snapshot directories under ``ckpt_dir``, newest last."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(os.path.join(ckpt_dir, n)
                  for n in os.listdir(ckpt_dir)
                  if n.startswith(_SNAP_PREFIX)
                  and os.path.isdir(os.path.join(ckpt_dir, n)))


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------

class ElasticTrainer:
    """Run a static training program step-by-step, surviving device loss.

    ``feed_fn(step) -> feed dict`` is the data loader; ``step`` is the
    cursor recorded in every snapshot manifest, so resume re-reads
    exactly the batches the lost run would have.

    ``state_dict``: ``{name: Tensor}`` (see :func:`elastic_state_dict`)
    — snapshotted asynchronously every ``snapshot_every`` steps and
    restored in place on recovery.
    """

    def __init__(self, exe, program, feed_fn, fetch_list, *, state_dict,
                 ckpt_dir, snapshot_every=0, keep=2, manager=None,
                 max_restarts=2):
        self.exe = exe
        self.program = program
        self.feed_fn = feed_fn
        self.fetch_list = fetch_list
        self.state_dict = dict(state_dict)
        self.ckpt_dir = ckpt_dir
        self.snapshot_every = int(snapshot_every)
        self.keep = max(1, int(keep))
        self.manager = manager
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.lost_steps = 0
        self.mttr_ms = []
        self.recovery_to_first_step_ms = None
        self.last_resume_path = None
        self.last_resume_step = None
        self._writer = None
        self._writer_err = None
        self._recovered_at = None

    # -- detection --------------------------------------------------------
    def _world(self):
        plan = get_mesh_plan()
        return plan.size if plan is not None else 1

    def _probe_health(self):
        """Fault-site probes + heartbeat staleness, every step boundary."""
        try:
            fault_point("dist.host_preempt")
        except InjectedFault as e:
            raise DeviceLostError([], reason=str(e) or "host_preempt",
                                  preempted=True) from e
        for r in range(self._world()):
            try:
                fault_point(f"dist.device_lost.{r}")
            except InjectedFault as e:
                raise DeviceLostError([r], reason=str(e) or
                                      "device_lost") from e
        if self.manager is not None:
            dead = self.manager.dead_ranks()
            if dead:
                raise DeviceLostError(dead, reason="heartbeat staleness")

    @staticmethod
    def _escalate(exc):
        """Map a raw failure raised out of a step into DeviceLostError."""
        if isinstance(exc, DeviceLostError):
            return exc
        if isinstance(exc, CollectiveTimeoutError):
            return DeviceLostError(exc.missing or [],
                                   reason=f"collective watchdog: {exc}",
                                   preempted=not exc.missing)
        return DeviceLostError([], reason=str(exc), preempted=True)

    # -- snapshots --------------------------------------------------------
    def _snapshot_due(self, completed):
        return (self.snapshot_every > 0 and completed > 0
                and completed % self.snapshot_every == 0)

    def snapshot(self, completed):
        """Capture on the caller's thread, commit on a background one."""
        self._join_writer()
        with obs.span("ckpt:snapshot", cat="ckpt", step=completed):
            _pipeline.drain()
            host, meta, nbytes = _capture_host_state(self.state_dict)
            train_meta = {"step": int(completed),
                          "rng_key": _rng_state_host().tolist(),
                          "data_cursor": int(completed)}
        register_resident(_STAGING_ITEM, nbytes, host=True)
        path = os.path.join(self.ckpt_dir,
                            f"{_SNAP_PREFIX}{completed:08d}")

        def _body():
            try:
                with obs.span("ckpt:write", cat="ckpt", step=completed,
                              bytes=nbytes):
                    _write_snapshot(path, host, meta, train_meta)
                if self.manager is not None:
                    try:
                        self.manager.record_checkpoint(
                            path, int(completed), validate=False)
                    except Exception:
                        pass
                self._prune()
            except BaseException as e:  # surfaced on next join
                self._writer_err = e
            finally:
                unregister_resident(_STAGING_ITEM, host=True)

        self._writer = threading.Thread(
            target=_body, name="elastic-snapshot", daemon=True)
        self._writer.start()
        return path

    def _join_writer(self):
        w, self._writer = self._writer, None
        if w is not None:
            w.join()
        err, self._writer_err = self._writer_err, None
        if err is not None:
            import warnings
            warnings.warn(f"async snapshot failed: {err!r}",
                          RuntimeWarning, stacklevel=2)

    def _prune(self):
        snaps = list_snapshots(self.ckpt_dir)
        for path in snaps[: max(0, len(snaps) - self.keep)]:
            shutil.rmtree(path, ignore_errors=True)

    # -- recovery ---------------------------------------------------------
    def _surviving_devices(self, plan, lost_ranks):
        devs = list(np.asarray(plan.mesh.devices).ravel())
        return [d for i, d in enumerate(devs) if i not in set(lost_ranks)]

    def _pick_checkpoint(self):
        """Newest *valid* snapshot; invalid ones are skipped with a
        recorded ``ckpt.corrupt`` instant (torn write / bit-rot)."""
        for path in reversed(list_snapshots(self.ckpt_dir)):
            ok, reasons = validate_checkpoint(path)
            if ok:
                return path
            if obs.enabled():
                obs.instant("ckpt.corrupt", cat="fault", path=path,
                            reasons="; ".join(reasons))
        return None

    def restore(self, path, plan=None):
        """Re-place the snapshot under ``plan`` (default: active plan)
        and restore step counter / RNG / cursor from its manifest.
        Returns the step to resume from."""
        import jax.numpy as jnp
        plan = plan if plan is not None else get_mesh_plan()
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        all_shards = {}
        for fname in sorted(os.listdir(path)):
            if fname.startswith("shard_") and fname.endswith(".pkl"):
                with open(os.path.join(path, fname), "rb") as f:
                    for name, pieces in pickle.load(f).items():
                        all_shards.setdefault(name, []).extend(pieces)
        named_shapes = {n: tuple(m["global_shape"])
                        for n, m in meta.items() if m["type"] == "tensor"}
        shard_fns = {}
        if plan is not None and not plan.is_virtual:
            shard_fns, _ = make_shard_and_gather_fns(plan, named_shapes)
        for name, t in self.state_dict.items():
            m = meta.get(name)
            if m is None or m["type"] != "tensor":
                continue
            full = np.zeros(m["global_shape"],
                            np.float32 if m["dtype"] == "bfloat16"
                            else np.dtype(m["dtype"]))
            for piece in all_shards.get(name, []):
                idx = tuple(slice(a, b) for a, b in piece["index"])
                full[idx] = piece["data"]
            val = jnp.asarray(full, t._value.dtype)
            if name in shard_fns:
                val = shard_fns[name](val)
            t._inplace_update(val)
        train = read_train_meta(path) or {}
        if train.get("rng_key") is not None:
            _set_rng_state_host(train["rng_key"])
        return int(train.get("step", 0))

    def _recover(self, err, failed_step):
        t0 = time.perf_counter()
        if obs.enabled():
            obs.instant("elastic.device_lost", cat="recovery",
                        ranks=",".join(map(str, err.lost_ranks)),
                        preempted=err.preempted, step=failed_step,
                        reason=err.reason)
        reg = obs.get_registry()
        reg.counter("elastic.restarts").inc()
        self.restarts += 1
        if self.restarts > self.max_restarts:
            raise err
        # abort the step cleanly: no leaked in-flight buffers, staging
        # line item released even if the writer died mid-commit
        with obs.span("recovery:abort", cat="recovery"):
            try:
                _pipeline.drain()
            except Exception:
                pass
            self._join_writer()
            try:
                unregister_resident(_STAGING_ITEM, host=True)
            except Exception:
                pass
        plan = get_mesh_plan()
        if plan is not None and err.lost_ranks and not err.preempted:
            with obs.span("recovery:shrink", cat="recovery",
                          mesh=plan.describe()):
                survivors = self._surviving_devices(plan, err.lost_ranks)
                plan = plan.shrink(survivors)
                set_mesh_plan(plan)
        path = self._pick_checkpoint()
        if path is None:
            raise DeviceLostError(
                err.lost_ranks,
                reason=f"{err.reason}; no valid snapshot to resume from",
                preempted=err.preempted)
        with obs.span("recovery:restore", cat="recovery", path=path):
            resume = self.restore(path, plan)
        self.last_resume_path = path
        self.last_resume_step = resume
        lost = max(0, failed_step - resume)
        self.lost_steps += lost
        reg.counter("elastic.lost_steps").inc(lost)
        ms = (time.perf_counter() - t0) * 1e3
        self.mttr_ms.append(ms)
        reg.histogram("elastic.mttr_ms").observe(ms)
        self._recovered_at = t0
        return resume

    # -- the loop ---------------------------------------------------------
    def run(self, n_steps, start_step=0):
        """Supervised training loop: ``start_step .. n_steps-1``, with
        health probes, periodic async snapshots, and recovery.  Returns
        the last step's fetches as numpy."""
        step = int(start_step)
        outs = None
        while step < n_steps:
            try:
                self._probe_health()
                outs = self.exe.run(self.program,
                                    feed=self.feed_fn(step),
                                    fetch_list=self.fetch_list,
                                    return_numpy=False)
                step += 1
                if self._recovered_at is not None:
                    _pipeline.drain()
                    self.recovery_to_first_step_ms = round(
                        (time.perf_counter() - self._recovered_at) * 1e3,
                        3)
                    self._recovered_at = None
                if self._snapshot_due(step):
                    self.snapshot(step)
            except (DeviceLostError, CollectiveTimeoutError,
                    InjectedFault) as e:
                step = self._recover(self._escalate(e), step)
                outs = None
        _pipeline.drain()
        self._join_writer()
        return [np.asarray(o) for o in outs] if outs else outs

    def stats(self):
        return {"restarts": self.restarts,
                "lost_steps": self.lost_steps,
                "mttr_ms": [round(v, 3) for v in self.mttr_ms],
                "recovery_to_first_step_ms":
                    self.recovery_to_first_step_ms}


# ---------------------------------------------------------------------------
# The chaos drill (shared by scripts/chaos_smoke.py, bench.py, tests)
# ---------------------------------------------------------------------------

def run_elastic_drill(n_steps=8, kill_step=5, kill_rank=3,
                      snapshot_every=2, seed=7, ckpt_dir=None):
    """Kill a device mid-run on a dp=4 host mesh, shrink to dp=2,
    restore, resume — and assert bit-parity against a clean run started
    from the same checkpoint on the shrunk mesh.

    Needs >= 4 jax devices (use ``--xla_force_host_platform_device_count``).
    Returns a report dict; ``report["ok"]`` is the gate verdict.
    """
    import tempfile

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu import static
    from paddle_tpu.models import BertConfig, BertForMaskedLM
    from .auto_parallel.sharding import (BERT_RULES, MeshPlan,
                                         annotate_params, clear_mesh_plan)
    from .fault_tolerance.plan import FaultPlan, inject
    from ..memory.guard import host_resident_items
    from ..static.executor import Executor

    if jax.device_count() < 4:
        raise RuntimeError(
            f"elastic drill needs >= 4 devices, have {jax.device_count()};"
            " set XLA_FLAGS=--xla_force_host_platform_device_count=8")
    B, S, V = 8, 16, 256
    tmp = None
    if ckpt_dir is None:
        tmp = tempfile.mkdtemp(prefix="elastic_drill_")
        ckpt_dir = tmp

    def _feed(step):
        rng = np.random.default_rng(seed * 7919 + step)
        return {"ids": rng.integers(0, V, (B, S)).astype(np.int64),
                "labels": rng.integers(0, V, (B, S)).astype(np.int64)}

    def _build(plan):
        """Fresh program + model + optimizer under ``plan``."""
        set_mesh_plan(plan)
        main_prog, startup = static.Program(), static.Program()
        with static.program_guard(main_prog, startup):
            ids = static.data("ids", [B, S], "int64")
            labels = static.data("labels", [B, S], "int64")
            model = BertForMaskedLM(BertConfig(
                vocab_size=V, hidden_size=32, num_hidden_layers=1,
                num_attention_heads=2, intermediate_size=64,
                max_position_embeddings=S))
            annotate_params(model)
            loss, _ = model(ids, labels=labels)
            opt = popt.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
            opt.minimize(loss)
        exe = Executor()
        exe.run(startup)
        # materialize moment accumulators now (normally lazy, first
        # dispatch) so the snapshot state_dict covers them from step 0
        opt._ensure_static_state(
            [p for p in model.parameters() if not p.stop_gradient])
        return exe, main_prog, model, opt, loss

    paddle.enable_static()
    report = {}
    try:
        paddle.seed(seed)
        plan = MeshPlan("dp=4", rules=BERT_RULES())
        exe, prog, model, opt, loss = _build(plan)
        state = elastic_state_dict(model, opt)
        trainer = ElasticTrainer(
            exe, prog, _feed, [loss], state_dict=state,
            ckpt_dir=ckpt_dir, snapshot_every=snapshot_every,
            keep=max(8, n_steps))
        fp = FaultPlan()
        fp.add(f"dist.device_lost.{kill_rank}", "kill",
               after=kill_step, count=1)
        t0 = time.perf_counter()
        with inject(fp):
            outs = trainer.run(n_steps)
        elastic_wall_s = time.perf_counter() - t0
        shrunk = get_mesh_plan()
        elastic_params = {n: np.asarray(t._value)
                          for n, t in state.items()}
        stats = trainer.stats()
        window_len = len(_pipeline.get_window())
        leaked_host = [n for n, _ in host_resident_items()
                       if n == _STAGING_ITEM]

        # clean reference: a FRESH model/program on the shrunk topology,
        # restored from the SAME snapshot the recovery used, run to the
        # same final step — final state must be bit-identical
        resume_path = trainer.last_resume_path
        clear_mesh_plan()
        Executor.clear_shared_cache()
        paddle.seed(seed)
        plan2 = MeshPlan(dict(shrunk.axis_sizes), rules=BERT_RULES(),
                         devices=list(
                             np.asarray(shrunk.mesh.devices).ravel()))
        exe2, prog2, model2, opt2, loss2 = _build(plan2)
        state2 = elastic_state_dict(model2, opt2)
        # positional rename: fresh session counters give the clean
        # model different auto-generated names; order is identical
        remap = dict(zip(state2.keys(), state.keys()))
        state2 = {remap[k]: t for k, t in state2.items()}
        ref = ElasticTrainer(exe2, prog2, _feed, [loss2],
                             state_dict=state2, ckpt_dir=ckpt_dir,
                             snapshot_every=0)
        resume = ref.restore(resume_path, plan2)
        for step in range(resume, n_steps):
            exe2.run(prog2, feed=_feed(step), fetch_list=[loss2])
        clean_params = {n: np.asarray(t._value)
                        for n, t in state2.items()}

        mismatch = [n for n in elastic_params
                    if n in clean_params
                    and elastic_params[n].tobytes()
                    != clean_params[n].tobytes()]
        parity = not mismatch and len(elastic_params) == len(clean_params)
        phases = obs.phase_breakdown() if obs.enabled() else {}
        report = {
            "ok": bool(parity and stats["restarts"] == 1
                       and window_len == 0 and not leaked_host
                       and shrunk.axis_size("dp") == 2
                       and resume == trainer.last_resume_step
                       and resume < n_steps),
            "parity": parity,
            "mismatched_params": mismatch[:5],
            "mesh_before": "dp=4",
            "mesh_after": shrunk.describe(),
            "resume_step": trainer.last_resume_step,
            "replayed_steps": n_steps - resume,
            "restarts": stats["restarts"],
            "lost_steps": stats["lost_steps"],
            "mttr_ms": stats["mttr_ms"],
            "recovery_to_first_step_ms":
                stats["recovery_to_first_step_ms"],
            "window_len": window_len,
            "leaked_host_items": leaked_host,
            "elastic_wall_s": round(elastic_wall_s, 3),
            "final_loss": float(np.asarray(outs[0])) if outs else None,
            "phases": phases,
        }
        return report
    finally:
        clear_mesh_plan()
        Executor.clear_shared_cache()
        paddle.disable_static()
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
