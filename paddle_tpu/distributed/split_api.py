"""paddle.distributed.split: functional Megatron-split helper.

Reference parity: `python/paddle/distributed/collective.py::split`
(builds a vocab/column/row-parallel layer over the mp group and applies
it; weights are created on first call and cached by name [UNVERIFIED —
empty reference mount]).  Delegates to the placement-based mp layers in
fleet.meta_parallel.
"""
from __future__ import annotations

__all__ = ["split", "reset_split_cache"]

_SPLIT_LAYERS: dict = {}


def reset_split_cache():
    """Release all layers (and sharded weights) split() has created."""
    _SPLIT_LAYERS.clear()


def split(x, size, operation="linear", axis=0, num_partitions=None,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Apply a model-parallel layer of the given kind to x.

    operation="embedding" → VocabParallelEmbedding(size);
    operation="linear", axis=1 → ColumnParallelLinear (weight columns
    split over mp); axis=0 → RowParallelLinear.  An UNNAMED call always
    builds a fresh layer (the reference's build-time contract: every
    call site owns its parameters); pass `name` to reuse one layer —
    and its weights — across repeated calls in an eager loop.
    """
    # Reference semantics: split() is a BUILD-time API — each call site
    # creates its own parameters.  Unnamed calls therefore always build
    # a fresh layer (two anonymous projections must not share weights);
    # pass `name` to reuse one layer across steps in an eager loop.
    # A named hit is validated against the full signature including the
    # attr objects so a changed initializer cannot be silently ignored.
    def _attr_sig(attr, _depth=0):
        # compare attrs by CONFIG, not identity: a fresh-but-identical
        # initializer each step must hit the cache.  Recurse into
        # nested config objects (ParamAttr.initializer etc.) — their
        # default repr embeds the memory address and would never match.
        if attr is None or isinstance(attr, (bool, int, float, str)):
            return attr
        if _depth > 4 or not hasattr(attr, "__dict__"):
            return (type(attr).__name__,)
        return (type(attr).__name__,
                tuple(sorted((k, _attr_sig(v, _depth + 1))
                             for k, v in vars(attr).items())))

    key = None
    layer = None
    if name is not None:
        key = (name, operation, tuple(size), axis, gather_out)
        entry = _SPLIT_LAYERS.get(key)
        if entry is not None:
            layer, prev_w, prev_b = entry
            if prev_w != _attr_sig(weight_attr) or \
                    prev_b != _attr_sig(bias_attr):
                raise ValueError(
                    f"split(name={name!r}): weight_attr/bias_attr "
                    "differ from the cached layer's; use a new name")
    if layer is None:
        from .fleet.meta_parallel import (ColumnParallelLinear,
                                          RowParallelLinear,
                                          VocabParallelEmbedding)
        if operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr,
                                           name=name)
        elif operation == "linear" and axis == 1:
            layer = ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out, name=name)
        elif operation == "linear" and axis == 0:
            layer = RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=not gather_out, name=name)
        else:
            raise ValueError(
                f"split: unsupported operation={operation!r} axis={axis}")
        if key is not None:
            _SPLIT_LAYERS[key] = (layer, _attr_sig(weight_attr),
                                  _attr_sig(bias_attr))
    return layer(x)
