"""paddle.distributed parity surface (Fleet stack).

Built in layers (SURVEY.md §2.3):
  env.py            — rank/world/mesh, multi-controller init
  communication/    — collective API (all_reduce/all_gather/... over mesh)
  parallel.py       — DataParallel
  fleet/            — fleet facade, HybridCommunicateGroup, meta_parallel
  auto_parallel/    — shard_tensor / ProcessMesh / Shard/Replicate
  launch/           — python -m paddle_tpu.distributed.launch
  checkpoint/       — sharded save/load with resharding
  fault_tolerance/  — fault injection, collective watchdog, retry,
                      crash-safe checkpoint primitives
"""
from .env import (init_parallel_env, get_rank, get_world_size,
                  is_initialized, global_mesh, set_global_mesh, ParallelEnv)
from .communication.group import (Group, new_group, get_group,
                                  destroy_process_group)
from .communication.all_reduce import all_reduce
from .communication.ops import (all_gather, all_gather_object, broadcast,
                                reduce, scatter, alltoall, alltoall_single,
                                send, recv, isend, irecv, barrier,
                                reduce_scatter, stream, P2POp,
                                batch_isend_irecv, wait, gather,
                                broadcast_object_list,
                                scatter_object_list, monitored_barrier)
from .communication.reduce_op import ReduceOp
from .parallel import DataParallel
from . import fleet
from . import auto_parallel
from .auto_parallel.engine import Strategy, DistModel, to_static
from .auto_parallel.api import (shard_tensor, shard_op, ProcessMesh, Shard,
                                Replicate, Partial, dtensor_from_fn,
                                reshard, shard_layer)
from . import checkpoint
from .checkpoint.save_load import save_state_dict, load_state_dict
from .store import (LocalStore, ResilientStore, StoreEpochError,
                    StoreLease, StoreTimeoutError, TCPStore)
from .split_api import split
from . import utils
from . import fault_tolerance
from .elastic_train import (DeviceLostError, ElasticTrainer,
                            elastic_state_dict)

spawn = None  # set by launch module


def get_backend():
    return "xla"  # ICI/DCN collectives via XLA (reference: nccl)


def is_available():
    return True
