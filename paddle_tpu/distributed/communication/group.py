"""Process groups as named mesh axes.

Reference parity: `python/paddle/distributed/communication/group.py` +
ProcessGroupNCCL (`fluid/distributed/collective/`) [UNVERIFIED — empty
reference mount].

TPU-native: a Group names a mesh axis (SURVEY.md §5 mapping: ProcessGroup/
new_group → Mesh + named axes).  Collectives inside shard_map regions
resolve the axis by name; rank enumeration maps onto positions along that
axis of the global mesh.
"""
from __future__ import annotations

import jax
import numpy as np

from ..env import get_rank, get_world_size, global_mesh

__all__ = ["Group", "new_group", "get_group", "destroy_process_group",
           "is_available", "wait_group"]

_groups: dict[int, "Group"] = {}
_next_gid = [0]


class Group:
    def __init__(self, ranks=None, gid=None, axis_name=None, mesh=None):
        self.id = gid if gid is not None else _next_gid[0]
        _next_gid[0] = max(_next_gid[0], self.id) + 1
        world = get_world_size()
        self.ranks = list(ranks) if ranks is not None else \
            list(range(world))
        self.nranks = len(self.ranks)
        self.axis_name = axis_name  # mesh axis this group reduces over
        self.mesh = mesh
        _groups[self.id] = self

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank=None):
        r = get_rank() if rank is None else rank
        return self.ranks.index(r) if r in self.ranks else -1

    @property
    def process_group(self):
        return self

    def is_member(self):
        return get_rank() in self.ranks

    def __repr__(self):
        return (f"Group(id={self.id}, nranks={self.nranks}, "
                f"axis={self.axis_name})")


_default_group = None


def _get_default_group():
    global _default_group
    if _default_group is None:
        mesh = global_mesh()
        axis = mesh.axis_names[0] if mesh.axis_names else None
        _default_group = Group(list(range(get_world_size())), gid=0,
                               axis_name=axis, mesh=mesh)
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """Create a sub-group.  `axis_name` binds it to a mesh axis so that
    collectives inside shard_map lower to that axis.

    A ranks-only subgroup (no axis_name) is honored when the ranks form a
    contiguous row/column of the global mesh along one axis — the axis is
    inferred.  Otherwise raise: collectives on an unbindable subgroup
    would silently degrade to no-ops (VERDICT r1 weak #10).
    """
    if ranks is not None and axis_name is None:
        world = get_world_size()
        rs = sorted(ranks)
        mesh = global_mesh()
        mesh_n = int(mesh.devices.size) if mesh is not None else 0
        if rs == list(range(world)) or (mesh_n and
                                        rs == list(range(mesh_n))):
            # the whole world / whole mesh: an all-axes group (a
            # topology smaller than the hardware still counts).  Tuple
            # axis names so in-scope collectives reduce over EVERY mesh
            # axis, not just the first (jax.lax.psum accepts tuples).
            if mesh is not None and mesh.axis_names:
                axis_name = (mesh.axis_names[0]
                             if len(mesh.axis_names) == 1
                             else tuple(mesh.axis_names))
            else:
                axis_name = None
        else:
            axis_name = _infer_axis_for_ranks(rs)
            if axis_name is None and len(rs) > 1:
                raise ValueError(
                    f"new_group(ranks={ranks}): these ranks do not lie "
                    f"along a single axis of the global mesh, so no "
                    f"mesh-axis collective can implement the subgroup. "
                    f"Pass axis_name= for a mesh axis, or build the mesh "
                    f"(fleet.init/topology) so the subgroup maps to an "
                    f"axis.")
    return Group(ranks, axis_name=axis_name, mesh=global_mesh())


def _infer_axis_for_ranks(rs):
    """Return the mesh axis whose coordinate varies (alone) over `rs`."""
    mesh = global_mesh()
    if mesh is None or not mesh.axis_names:
        return None
    try:
        ids = np.arange(int(np.prod(mesh.devices.shape))).reshape(
            mesh.devices.shape)
    except Exception:
        return None
    for ax, name in enumerate(mesh.axis_names):
        # collect coordinate tuples of rs; they match one axis iff all
        # other coordinates are constant and this axis covers the set
        coords = [np.argwhere(ids == r)[0] for r in rs if (ids == r).any()]
        if len(coords) != len(rs):
            return None
        others_const = all(
            all(c[i] == coords[0][i] for i in range(len(c)) if i != ax)
            for c in coords)
        axis_vals = sorted(int(c[ax]) for c in coords)
        if others_const and axis_vals == list(range(ids.shape[ax])):
            return name
    return None


def get_group(gid=0):
    if gid == 0:
        return _get_default_group()
    return _groups.get(gid)


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def is_available():
    return True


def wait_group(tensor=None, group=None, use_calc_stream=True):
    if tensor is not None:
        try:
            tensor._value.block_until_ready()
        except Exception:
            pass
