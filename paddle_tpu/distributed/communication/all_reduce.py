"""all_reduce (kept in its own module for paddle path parity).

Reference parity: `python/paddle/distributed/communication/all_reduce.py`
[UNVERIFIED — empty reference mount].
"""
from .ops import all_reduce

__all__ = ["all_reduce"]
