"""Collective API over mesh axes.

Reference parity: `python/paddle/distributed/communication/*.py` routed to
ProcessGroupNCCL / `c_*` ops [UNVERIFIED — empty reference mount].

TPU-native mapping (SURVEY.md §5): c_allreduce→psum, c_allgather→
all_gather, c_reducescatter→psum_scatter, send/recv(PP)→ppermute,
global_scatter/gather(EP)→all_to_all — all as jax.lax collectives resolved
by the group's mesh-axis name.

Execution contexts:
  * inside a shard_map region (named axis in scope): true ICI collectives;
  * eager with world_size==1 (single chip / tests): identity semantics;
  * eager multi-device: arrays are global (single-controller SPMD) — data
    is already globally visible, so all_reduce/broadcast reduce to
    arithmetic on the global array.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ... import observability as obs
from ...core.dispatch import dispatch
from ...core.tensor import Tensor, to_tensor
from ..fault_tolerance.watchdog import get_watchdog
from .group import Group, _get_default_group
from .reduce_op import ReduceOp

__all__ = ["all_gather", "all_gather_object", "broadcast", "reduce",
           "scatter", "alltoall", "alltoall_single", "send", "recv",
           "isend", "irecv", "barrier", "reduce_scatter", "stream", "P2POp",
           "batch_isend_irecv", "wait", "gather",
           "broadcast_object_list", "scatter_object_list",
    "monitored_barrier",
]


def _trace_clean():
    """True when we're in plain eager execution (no jit/shard_map trace
    in flight).  The watchdog only wraps eager entry points: inside a
    trace XLA owns the collective and thread-hopping the trace context
    would corrupt it."""
    try:
        return jax.core.trace_state_clean()
    except Exception:
        return True


def _payload_bytes(args, kwargs):
    """Total tensor payload of a collective call (obs-enabled only):
    Tensor args plus tensors inside list args (all_gather/alltoall)."""
    n = 0

    def add(t):
        nonlocal n
        try:
            v = t._value
            n += int(v.size) * v.dtype.itemsize
        except Exception:
            pass

    for a in list(args) + list(kwargs.values()):
        if isinstance(a, Tensor):
            add(a)
        elif isinstance(a, (list, tuple)):
            for t in a:
                if isinstance(t, Tensor):
                    add(t)
    return n


# payload signatures already linted, so a hot loop records each
# TPU403 pattern once per process rather than per call
_lint_seen: set = set()


def _lint_payload(op_name, args, group=None):
    """Runtime tpu_lint of a collective payload (TPU403: mixed
    shapes/dtypes in a tensor list, f64 on the wire; TPU503: payload
    dim not divisible by the group's mesh-axis size)."""
    tensors = []
    for a in args:
        if isinstance(a, Tensor):
            tensors.append(a)
        elif isinstance(a, (list, tuple)):
            tensors.extend(t for t in a if isinstance(t, Tensor))
    if not tensors:
        return
    try:
        sig = (op_name, tuple(
            (tuple(getattr(t._value, "shape", ())),
             str(getattr(t._value, "dtype", "?"))) for t in tensors),
            getattr(group, "nranks", None))
    except Exception:
        return
    if sig in _lint_seen:
        return
    _lint_seen.add(sig)
    from ...analysis import (check_collective_axis,
                             check_collective_payload, record)
    for d in check_collective_payload(op_name, tensors):
        record(d)
    if group is not None:
        site = f"{op_name}(group={group.id}, " \
               f"axis={getattr(group, 'axis_name', None)})"
        for d in check_collective_axis(op_name, tensors, group.nranks,
                                       site=site):
            record(d)


def _watched(op_name):
    """Collective-watchdog wrapper (fault_tolerance layer) + telemetry.

    Disabled (the default) this is two global reads per call.  With the
    watchdog enabled (enable_watchdog() / PADDLE_TPU_WATCHDOG_TIMEOUT),
    the op body runs under a deadline and a timeout raises
    CollectiveTimeoutError naming the op, the group, and which ranks
    checked in — instead of hanging the training job forever on a dead
    peer.  With observability collecting (PADDLE_TPU_OBS), every eager
    entry records a ``collective`` span carrying duration + payload
    bytes + group size on the shared step timeline."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            g = None
            if obs.enabled() and _trace_clean():
                g = kwargs.get("group")
                if g is None:
                    g = next((a for a in args if isinstance(a, Group)),
                             None)
                g = g if g is not None else _group(None)
                sp = obs.span("collective:" + op_name, cat="collective",
                              bytes=_payload_bytes(args, kwargs),
                              nranks=g.nranks, group=g.id,
                              axis=str(g.axis_name)
                              if g.axis_name is not None else None)
                _lint_payload(op_name, args, g)
            else:
                sp = obs._NULL_SPAN
            with sp:
                wd = get_watchdog()
                if wd is None or not _trace_clean():
                    return fn(*args, **kwargs)
                if g is None:
                    g = kwargs.get("group")
                    if g is None:
                        g = next((a for a in args
                                  if isinstance(a, Group)), None)
                    g = g if g is not None else _group(None)
                return wd.run(lambda: fn(*args, **kwargs), op_name,
                              group=g)
        return wrapper
    return deco


def _axis_in_scope(axis_name):
    if axis_name is None:
        return False
    names = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    try:
        for n in names:  # whole-mesh groups carry a tuple of axes
            jax.lax.axis_index(n)
        return True
    except (NameError, Exception):
        return False


def _group(group):
    return group if group is not None else _get_default_group()


def _is_replicated(tensor) -> bool:
    try:
        return tensor._value.sharding.is_fully_replicated
    except Exception:
        return True


def _eager_guard(g, op_name, tensor=None):
    """Honesty check for eager collectives outside a shard_map region.

    Under single-controller SPMD a fully-replicated jax.Array already IS
    the group-global value, so identity semantics are correct.  A
    non-replicated (genuinely per-shard) input would get silently wrong
    results from an identity fallback — raise instead (VERDICT r1 weak
    #3: ops.py's silent no-ops).
    """
    if g.nranks <= 1:
        return
    if tensor is not None and _is_replicated(tensor):
        return
    raise RuntimeError(
        f"paddle.distributed.{op_name}: eager collective outside a "
        f"shard_map region with nranks={g.nranks} and a non-replicated "
        f"input. Identity fallback would be silently wrong. Run the "
        f"collective inside a shard_map scope bound to the group's mesh "
        f"axis (fleet hybrid-parallel does this), or keep values "
        f"replicated (sharding-based DataParallel).")


class _Work:
    """Completed-work handle (PJRT is async; wait == block_until_ready)."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None:
            try:
                self._tensor._value.block_until_ready()
            except Exception:
                pass
        return True

    def is_completed(self):
        return True


def _apply_inplace(tensor, new_tensor):
    tensor._inplace_update(new_tensor._value, new_tensor._grad_node,
                           new_tensor._out_index)
    return tensor


@_watched("all_reduce")
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce (see communication/all_reduce.py for docs)."""
    g = _group(group)
    axis = g.axis_name
    if _axis_in_scope(axis):
        def impl(v, *, axis, op):
            if op == ReduceOp.SUM:
                return jax.lax.psum(v, axis)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(v, axis)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(v, axis)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(v, axis)
            if op == ReduceOp.PROD:
                return jnp.exp(jax.lax.psum(jnp.log(v), axis))
            raise ValueError(op)

        out = dispatch("c_allreduce", impl, (tensor,),
                       dict(axis=axis, op=op))
        return _apply_inplace(tensor, out)
    _eager_guard(g, "all_reduce", tensor)
    # replicated global array: already the group-global value
    return tensor


@_watched("all_gather")
def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    g = _group(group)
    axis_name = g.axis_name
    if isinstance(tensor_list, Tensor):  # tensor-output variant
        return _all_gather_into(tensor_list, tensor, g)
    if _axis_in_scope(axis_name):
        def impl(v, *, axis_name):
            return jax.lax.all_gather(v, axis_name)

        out = dispatch("c_allgather", impl, (tensor,),
                       dict(axis_name=axis_name))
        from ...ops.manipulation import unbind
        parts = unbind(out, 0)
        tensor_list.clear()
        tensor_list.extend(parts)
        return _Work()
    if g.nranks <= 1:
        tensor_list.clear()
        tensor_list.append(tensor)
        return _Work(tensor)
    _eager_guard(g, "all_gather", tensor)
    tensor_list.clear()
    tensor_list.extend([tensor for _ in range(g.nranks)])
    return _Work(tensor)


def _all_gather_into(out_tensor, tensor, g):
    if _axis_in_scope(g.axis_name):
        def impl(v, *, axis_name):
            gathered = jax.lax.all_gather(v, axis_name)
            return gathered.reshape((-1,) + v.shape[1:])

        out = dispatch("c_allgather", impl, (tensor,),
                       dict(axis_name=g.axis_name))
        return _apply_inplace(out_tensor, out)
    _eager_guard(g, "all_gather", tensor)
    return _apply_inplace(out_tensor, tensor)


def all_gather_object(object_list, obj, group=None):
    g = _group(group)
    object_list.clear()
    object_list.extend([obj for _ in range(max(g.nranks, 1))])


@_watched("broadcast")
def broadcast(tensor, src=0, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        def impl(v, *, axis, src):
            # select src's value on every member of the axis
            idx = jax.lax.axis_index(axis)
            masked = jnp.where(idx == src, v, jnp.zeros_like(v))
            return jax.lax.psum(masked, axis)

        out = dispatch("c_broadcast", impl, (tensor,),
                       dict(axis=g.axis_name, src=g.get_group_rank(src)
                            if src in g.ranks else src))
        return _apply_inplace(tensor, out)
    _eager_guard(g, "broadcast", tensor)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on TPU a reduce is an all_reduce (result replicated; dst reads it)
    return all_reduce(tensor, op, group, sync_op)


@_watched("scatter")
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        from ...ops.manipulation import stack
        stacked = stack(tensor_list, 0) if tensor_list else tensor

        def impl(v, *, axis):
            idx = jax.lax.axis_index(axis)
            return jax.lax.dynamic_index_in_dim(v, idx, 0, keepdims=False)

        out = dispatch("c_scatter", impl, (stacked,),
                       dict(axis=g.axis_name))
        return _apply_inplace(tensor, out)
    if tensor_list:
        _eager_guard(g, "scatter", tensor_list[0])
        return _apply_inplace(tensor, tensor_list[g.rank if g.rank >= 0
                                                  else 0])
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    g = _group(group)
    lst = gather_list if gather_list is not None else []
    all_gather(lst, tensor, group)
    return lst


@_watched("reduce_scatter")
def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        from ...ops.manipulation import stack, concat
        inp = stack(tensor_list, 0) if isinstance(tensor_list, list) else \
            tensor_list

        def impl(v, *, axis):
            return jax.lax.psum_scatter(v, axis, scatter_dimension=0,
                                        tiled=False)

        out = dispatch("c_reducescatter", impl, (inp,),
                       dict(axis=g.axis_name))
        return _apply_inplace(tensor, out)
    if isinstance(tensor_list, list) and tensor_list:
        _eager_guard(g, "reduce_scatter", tensor_list[0])
        return _apply_inplace(tensor, tensor_list[g.rank if g.rank >= 0
                                                  else 0])
    return tensor


@_watched("alltoall")
def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        from ...ops.manipulation import stack, unbind
        stacked = stack(in_tensor_list, 0)

        def impl(v, *, axis):
            return jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0,
                                      tiled=False)

        out = dispatch("c_alltoall", impl, (stacked,),
                       dict(axis=g.axis_name))
        parts = unbind(out, 0) if not isinstance(out, (list, tuple)) else \
            out
        out_tensor_list.clear()
        out_tensor_list.extend(parts)
        return _Work()
    if in_tensor_list:
        _eager_guard(g, "alltoall", in_tensor_list[0])
    out_tensor_list.clear()
    out_tensor_list.extend(in_tensor_list)
    return _Work()


@_watched("alltoall_single")
def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        def impl(v, *, axis, n):
            parts = v.reshape((n, -1) + v.shape[1:])
            out = jax.lax.all_to_all(parts, axis, split_axis=0,
                                     concat_axis=0, tiled=False)
            return out.reshape((-1,) + v.shape[1:])

        out = dispatch("c_alltoall_single", impl, (in_tensor,),
                       dict(axis=g.axis_name, n=g.nranks))
        return _apply_inplace(out_tensor, out)
    _eager_guard(g, "alltoall_single", in_tensor)
    return _apply_inplace(out_tensor, in_tensor)


@_watched("send")
def send(tensor, dst=0, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        # point-to-point on TPU rides ppermute (collective_permute on ICI)
        def impl(v, *, axis, src, dst):
            return jax.lax.ppermute(v, axis, [(src, dst)])

        dispatch("send_v2", impl, (tensor,),
                 dict(axis=g.axis_name, src=g.rank, dst=dst))
        return _Work(tensor)
    if g.nranks > 1:
        raise RuntimeError(
            "paddle.distributed.send: point-to-point transfer outside a "
            "shard_map region cannot be expressed on TPU (no eager "
            "fallback is correct). Use ppermute inside shard_map — the "
            "pipeline-parallel schedule does this.")
    return _Work(tensor)


_p2p_buffer = {}


@_watched("recv")
def recv(tensor, src=0, group=None, sync_op=True):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        def impl(v, *, axis, src, dst):
            return jax.lax.ppermute(v, axis, [(src, dst)])

        out = dispatch("recv_v2", impl, (tensor,),
                       dict(axis=g.axis_name, src=src, dst=g.rank))
        return _apply_inplace(tensor, out)
    if g.nranks > 1:
        raise RuntimeError(
            "paddle.distributed.recv: point-to-point transfer outside a "
            "shard_map region cannot be expressed on TPU (no eager "
            "fallback is correct). Use ppermute inside shard_map — the "
            "pipeline-parallel schedule does this.")
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group, sync_op=False)
    return _Work(tensor)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Batched P2P (reference: `pp_utils/p2p_communication.py`).

    Delegates per-op: each send lowers to its own ppermute.  In COMPILED
    graphs XLA's CollectivePermuteCombiner merges adjacent permutes with
    disjoint pairs into one collective, so the fused-transfer behavior
    the reference hand-codes is recovered at compile time; the pipeline
    engine (pp_utils/spmd_schedule.py) emits a single ppermute directly
    and does not go through this compat shim.
    """
    works = []
    for op in p2p_op_list:
        if op.op in (send, isend):
            works.append(op.op(op.tensor, op.peer, op.group))
        else:
            works.append(op.op(op.tensor, op.peer, op.group))
    return works


@_watched("barrier")
def barrier(group=None):
    g = _group(group)
    if _axis_in_scope(g.axis_name):
        def impl(*, axis):
            return jax.lax.psum(jnp.ones(()), axis)

        dispatch("barrier", impl, (), dict(axis=g.axis_name))
        return
    try:
        (jnp.zeros(()) + 0).block_until_ready()
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    try:
        tensor._value.block_until_ready()
    except Exception:
        pass


class stream:
    """paddle.distributed.stream.* parity: same collectives, explicit
    sync_op/use_calc_stream flags (PJRT handles ordering)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    send = staticmethod(send)
    recv = staticmethod(recv)


def broadcast_object_list(object_list, src=0, group=None):
    """Single-controller SPMD: every rank lives in this process and the
    list is already identical on all of them (same shim contract as
    all_gather_object above).  Cross-PROCESS object exchange is the
    TCPStore's job (distributed/store.py)."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    g = _group(group)
    rank = g.rank if g.rank >= 0 else 0  # same convention as scatter()
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[rank % len(in_object_list)])
    return out_object_list


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Barrier with a real watchdog deadline: on expiry raises
    CollectiveTimeoutError naming the barrier and (when a store-backed
    watchdog is enabled) the ranks that checked in vs. went missing."""
    wd = get_watchdog()
    if wd is None or not _trace_clean():
        return barrier(group)
    # barrier.__wrapped__: don't nest a second watchdog thread
    return wd.run(lambda: barrier.__wrapped__(group), "monitored_barrier",
                  group=_group(group), timeout=timeout)
