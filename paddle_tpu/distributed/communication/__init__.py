from . import group, all_reduce, ops, reduce_op
