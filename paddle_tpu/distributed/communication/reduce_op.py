"""ReduceOp enum (paddle.distributed.ReduceOp parity)."""
from __future__ import annotations

__all__ = ["ReduceOp"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4
