"""Collective watchdog: bounded waits + rank-roster diagnostics.

A hung collective is the worst distributed failure mode: every healthy
rank parks inside XLA/NCCL-equivalent plumbing forever with zero signal
about *who* is missing.  The watchdog wraps the Python entry points of
``distributed/communication/ops.py``; each wrapped call

  1. checks in to the rendezvous store (``wd/<op>/<seq>/<rank>``) so
     peers can be audited post-mortem,
  2. runs the op body on a worker thread with a deadline,
  3. on expiry raises :class:`CollectiveTimeoutError` naming the op,
     the group, and exactly which ranks checked in vs. went missing —
     instead of hanging forever.

Off by default (zero overhead beyond one global read).  Enable with
``enable_watchdog(timeout=...)`` or ``PADDLE_TPU_WATCHDOG_TIMEOUT``.
Traced/compiled collectives (inside jit / shard_map) are never wrapped:
XLA owns those and thread-hopping would corrupt the trace context.
"""
from __future__ import annotations

import os
import threading

from .plan import fault_point

__all__ = ["CollectiveWatchdog", "CollectiveTimeoutError",
           "enable_watchdog", "disable_watchdog", "get_watchdog",
           "ENV_WATCHDOG_TIMEOUT"]

ENV_WATCHDOG_TIMEOUT = "PADDLE_TPU_WATCHDOG_TIMEOUT"


class CollectiveTimeoutError(RuntimeError):
    """A collective did not complete within the watchdog deadline.

    Carries the diagnostic roster: ``op``, ``group``, ``timeout``,
    ``checked_in`` (ranks that entered the op) and ``missing`` (ranks
    that never did) — when a store was available to audit them."""

    def __init__(self, op, group=None, timeout=None, checked_in=None,
                 missing=None):
        self.op = op
        self.group = group
        self.timeout = timeout
        self.checked_in = checked_in
        self.missing = missing
        roster = ""
        if checked_in is not None or missing is not None:
            roster = (f"; ranks checked in: {sorted(checked_in or [])}, "
                      f"missing: {sorted(missing or [])}")
        gdesc = f" on {group}" if group is not None else ""
        super().__init__(
            f"collective '{op}'{gdesc} timed out after {timeout}s"
            f"{roster}. A missing rank is likely dead or stuck — see "
            f"ElasticManager.dead_ranks() / the launcher log for which "
            f"worker to restart.")


class CollectiveWatchdog:
    """Deadline + roster audit for host-side collective entry points."""

    def __init__(self, timeout=None, store=None, rank=None,
                 world_size=None, key_prefix="wd"):
        if timeout is None:
            timeout = float(os.environ.get(ENV_WATCHDOG_TIMEOUT, "300"))
        self.timeout = float(timeout)
        self.store = store
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")) \
            if rank is None else int(rank)
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) \
            if world_size is None else int(world_size)
        self.key_prefix = key_prefix
        self._seq = {}
        self._lock = threading.Lock()

    # -- roster ----------------------------------------------------------
    def _op_seq(self, op_name):
        with self._lock:
            n = self._seq[op_name] = self._seq.get(op_name, 0) + 1
        return n

    def _checkin(self, op_name, seq):
        if self.store is None:
            return
        try:
            self.store.set(
                f"{self.key_prefix}/{op_name}/{seq}/{self.rank}", b"1")
        except Exception:
            pass  # diagnostics must never fail the op itself

    def _roster(self, op_name, seq):
        if self.store is None:
            return None, None
        checked, missing = [], []
        for r in range(self.world_size):
            try:
                present = self.store.query(
                    f"{self.key_prefix}/{op_name}/{seq}/{r}") is not None
            except Exception:
                present = False
            (checked if present else missing).append(r)
        return checked, missing

    # -- execution -------------------------------------------------------
    def run(self, fn, op_name, group=None, timeout=None):
        """Run ``fn()`` under the deadline; re-raise its exception or
        raise CollectiveTimeoutError with the rank roster on expiry."""
        deadline = self.timeout if timeout is None else float(timeout)
        if deadline <= 0:
            fault_point("collective." + op_name)
            return fn()
        seq = self._op_seq(op_name)
        self._checkin(op_name, seq)
        box = {}
        done = threading.Event()

        def _target():
            try:
                # stall/drop faults land inside the watched region so
                # the deadline (not the caller) observes them
                fault_point("collective." + op_name)
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_target, daemon=True,
                             name=f"watchdog-{op_name}-{seq}")
        t.start()
        if not done.wait(deadline):
            checked, missing = self._roster(op_name, seq)
            from ... import observability as obs
            obs.instant("fault.watchdog_timeout", cat="fault",
                        op=op_name, timeout=deadline,
                        checked_in=checked, missing=missing)
            raise CollectiveTimeoutError(op_name, group=group,
                                         timeout=deadline,
                                         checked_in=checked,
                                         missing=missing)
        if "error" in box:
            raise box["error"]
        return box.get("value")


# -- global instance -----------------------------------------------------
_watchdog = None
_env_checked = False


def enable_watchdog(timeout=None, store=None, rank=None, world_size=None):
    """Install the process-global watchdog; returns it."""
    global _watchdog
    _watchdog = CollectiveWatchdog(timeout=timeout, store=store, rank=rank,
                                   world_size=world_size)
    return _watchdog


def disable_watchdog():
    global _watchdog, _env_checked
    _watchdog = None
    _env_checked = True  # explicit disable beats the env var


def get_watchdog():
    """The enabled watchdog, else one auto-enabled from
    ``PADDLE_TPU_WATCHDOG_TIMEOUT`` (checked once), else None."""
    global _watchdog, _env_checked
    if _watchdog is not None:
        return _watchdog
    if not _env_checked:
        _env_checked = True
        if os.environ.get(ENV_WATCHDOG_TIMEOUT):
            _watchdog = CollectiveWatchdog()
    return _watchdog
