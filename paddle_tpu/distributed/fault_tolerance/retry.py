"""Retry/backoff primitives shared by the rendezvous, elastic, and
serving layers.

Exponential backoff with *deterministic* jitter: the jitter sequence
comes from a seeded RNG so a replayed run (same seed) sleeps the same
schedule — required for the FaultPlan replay contract.  The default
seed derives from the rank so a thundering herd of restarting workers
still decorrelates.

:class:`RetryPolicy` packages one backoff schedule (max attempts,
jittered exponential, injectable clock/sleep) as a reusable object so
every consumer — ``retry_call``, the TCPStore connect loop, the serving
fleet's replica-probation re-admission — shares ONE implementation
instead of hand-rolling its own loop.
"""
from __future__ import annotations

import os
import random
import time

__all__ = ["backoff_delays", "retry_call", "RetryPolicy",
           "RetryExhausted", "ENV_STORE_RETRIES"]

ENV_STORE_RETRIES = "PADDLE_TPU_STORE_RETRIES"


class RetryExhausted(RuntimeError):
    """All attempts failed; ``.last`` carries the final exception."""

    def __init__(self, msg, last=None):
        super().__init__(msg)
        self.last = last


def _default_seed():
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def backoff_delays(base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
                   seed=None):
    """Yield an unbounded exponential backoff schedule.

    delay_i = min(base * factor**i, max_delay) * U(1-jitter, 1+jitter)
    with U drawn from a seeded RNG (deterministic per seed)."""
    rng = random.Random(_default_seed() if seed is None else seed)
    d = float(base)
    while True:
        j = 1.0 + jitter * (2.0 * rng.random() - 1.0) if jitter else 1.0
        yield min(d, max_delay) * j
        d *= factor


class RetryPolicy:
    """A reusable retry/backoff schedule (module doc).

    ``retries`` is the number of RE-tries (total attempts =
    retries + 1); ``retries=None`` means unbounded attempts — the loop
    is then capped only by the ``deadline`` passed to :meth:`call`.
    ``clock``/``sleep`` are injectable so consumers that schedule
    *future* re-admission times (the serving fleet's replica probation)
    are deterministic under test.
    """

    __slots__ = ("retries", "base", "factor", "max_delay", "jitter",
                 "seed", "clock", "sleep")

    def __init__(self, retries=3, base=0.05, factor=2.0, max_delay=2.0,
                 jitter=0.25, seed=None, clock=None, sleep=None):
        self.retries = None if retries is None else int(retries)
        self.base = float(base)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.seed = seed
        self.clock = clock or time.monotonic
        self.sleep = sleep or time.sleep

    def delays(self):
        """A FRESH deterministic delay generator (same seed → same
        schedule, so a replayed run backs off identically)."""
        return backoff_delays(self.base, self.factor, self.max_delay,
                              self.jitter, self.seed)

    def call(self, fn, exceptions=(OSError,), deadline=None,
             on_retry=None, what="operation"):
        """Call ``fn()`` under this policy.

        ``deadline`` is an absolute ``self.clock()`` cutoff that caps
        the whole loop; ``on_retry(attempt, exc)`` observes each
        failure (diagnostics / test hooks).  Raises
        :class:`RetryExhausted` (``.last`` holds the final exception)
        when attempts or the deadline run out."""
        delays = self.delays()
        last = None
        attempt = 0
        while True:
            try:
                return fn()
            except exceptions as e:
                last = e
                from ... import observability as obs
                obs.instant("fault.retry", cat="fault", what=what,
                            attempt=attempt,
                            error=f"{type(e).__name__}: {e}"[:200])
                if on_retry is not None:
                    on_retry(attempt, e)
                if self.retries is not None and attempt >= self.retries:
                    break
                delay = next(delays)
                if deadline is not None:
                    remaining = deadline - self.clock()
                    if remaining <= 0:
                        break
                    delay = min(delay, remaining)
                self.sleep(delay)
                attempt += 1
        n = "unbounded" if self.retries is None else self.retries + 1
        raise RetryExhausted(
            f"{what}: {n} attempts failed (last: {last})", last=last)

    def __repr__(self):
        return (f"RetryPolicy(retries={self.retries}, base={self.base}, "
                f"factor={self.factor}, max_delay={self.max_delay}, "
                f"jitter={self.jitter}, seed={self.seed})")


def retry_call(fn, exceptions=(OSError,), retries=3, deadline=None,
               base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
               seed=None, on_retry=None, what="operation"):
    """Call ``fn()`` with bounded retries and backoff — the functional
    shorthand over :class:`RetryPolicy` (see its docs for semantics)."""
    return RetryPolicy(retries, base, factor, max_delay, jitter,
                       seed).call(fn, exceptions=exceptions,
                                  deadline=deadline, on_retry=on_retry,
                                  what=what)
