"""Retry/backoff primitives shared by the rendezvous + elastic layers.

Exponential backoff with *deterministic* jitter: the jitter sequence
comes from a seeded RNG so a replayed run (same seed) sleeps the same
schedule — required for the FaultPlan replay contract.  The default
seed derives from the rank so a thundering herd of restarting workers
still decorrelates.
"""
from __future__ import annotations

import os
import random
import time

__all__ = ["backoff_delays", "retry_call", "RetryExhausted",
           "ENV_STORE_RETRIES"]

ENV_STORE_RETRIES = "PADDLE_TPU_STORE_RETRIES"


class RetryExhausted(RuntimeError):
    """All attempts failed; ``.last`` carries the final exception."""

    def __init__(self, msg, last=None):
        super().__init__(msg)
        self.last = last


def _default_seed():
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def backoff_delays(base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
                   seed=None):
    """Yield an unbounded exponential backoff schedule.

    delay_i = min(base * factor**i, max_delay) * U(1-jitter, 1+jitter)
    with U drawn from a seeded RNG (deterministic per seed)."""
    rng = random.Random(_default_seed() if seed is None else seed)
    d = float(base)
    while True:
        j = 1.0 + jitter * (2.0 * rng.random() - 1.0) if jitter else 1.0
        yield min(d, max_delay) * j
        d *= factor


def retry_call(fn, exceptions=(OSError,), retries=3, deadline=None,
               base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
               seed=None, on_retry=None, what="operation"):
    """Call ``fn()`` with bounded retries and backoff.

    ``retries`` is the number of RE-tries (total attempts = retries+1);
    ``deadline`` is an absolute ``time.monotonic()`` cutoff that caps
    the whole loop.  ``on_retry(attempt, exc)`` observes each failure
    (diagnostics / test hooks)."""
    delays = backoff_delays(base, factor, max_delay, jitter, seed)
    last = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            last = e
            from ... import observability as obs
            obs.instant("fault.retry", cat="fault", what=what,
                        attempt=attempt,
                        error=f"{type(e).__name__}: {e}"[:200])
            if on_retry is not None:
                on_retry(attempt, e)
            if attempt >= retries:
                break
            delay = next(delays)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                delay = min(delay, remaining)
            time.sleep(delay)
    raise RetryExhausted(
        f"{what}: {retries + 1} attempts failed (last: {last})", last=last)
