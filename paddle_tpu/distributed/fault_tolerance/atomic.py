"""Crash-safe file primitives for checkpointing.

The failure model is a worker dying MID-WRITE (preemption, OOM-kill,
pod teardown): a torn file must never be mistaken for a checkpoint.
Three layers of defense:

  * ``atomic_write``: tmp-file + fsync + ``os.replace`` — a file either
    has its complete new contents or doesn't exist; no torn states.
  * per-file sha256 sidecars + a ``manifest.json`` written LAST — a
    checkpoint directory is valid iff the manifest exists and every
    listed file's checksum matches (the manifest doubles as the commit
    record: no manifest ⇒ the save never finished).
  * ``latest_good_checkpoint``: scan a root for the newest directory
    that passes validation — the load-time fallback target.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os

__all__ = ["atomic_write", "file_sha256", "write_manifest",
           "validate_checkpoint", "latest_good_checkpoint",
           "CheckpointCorruptionError", "MANIFEST_NAME"]

MANIFEST_NAME = "manifest.json"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed validation; ``.path`` / ``.reasons`` say why."""

    def __init__(self, path, reasons):
        self.path = path
        self.reasons = list(reasons)
        super().__init__(
            f"corrupt/incomplete checkpoint at {path!r}: "
            + "; ".join(self.reasons))


def _fsync_dir(path):
    """fsync a directory so a completed rename survives power loss.
    Platforms that cannot open directories (or refuse to fsync them)
    are a no-op — the rename itself is still atomic."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path, mode="wb"):
    """Write to ``path`` all-or-nothing: stage into a same-directory tmp
    file, fsync, then ``os.replace`` (atomic on POSIX) and fsync the
    parent directory so the rename itself is durable.  On any error the
    tmp file is removed and ``path`` is untouched."""
    tmp = f"{path}.tmp.{os.getpid()}"
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def file_sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(ckpt_dir, files=None, extra=None):
    """Commit record: checksums of ``files`` (default: every regular
    file already in ``ckpt_dir``), written atomically and LAST."""
    if files is None:
        files = [n for n in sorted(os.listdir(ckpt_dir))
                 if n != MANIFEST_NAME
                 and os.path.isfile(os.path.join(ckpt_dir, n))]
    manifest = {"format": 1,
                "files": {n: file_sha256(os.path.join(ckpt_dir, n))
                          for n in files}}
    if extra:
        manifest.update(extra)
    with atomic_write(os.path.join(ckpt_dir, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def validate_checkpoint(ckpt_dir):
    """Returns (ok, reasons).  Valid ⇔ manifest present, every listed
    file present with a matching sha256."""
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isdir(ckpt_dir):
        return False, [f"not a directory: {ckpt_dir}"]
    if not os.path.exists(mpath):
        return False, ["no manifest (save never completed)"]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f"unreadable manifest: {e}"]
    reasons = []
    for name, want in manifest.get("files", {}).items():
        p = os.path.join(ckpt_dir, name)
        if not os.path.exists(p):
            reasons.append(f"missing file {name}")
            continue
        got = file_sha256(p)
        if got != want:
            reasons.append(f"checksum mismatch on {name} "
                           f"(want {want[:12]}…, got {got[:12]}…)")
    return (not reasons), reasons


def latest_good_checkpoint(root):
    """Newest (by mtime, then name) subdirectory of ``root`` that passes
    validation, or None.  ``root`` itself is considered too, so both
    layouts work: a directory-of-checkpoints and a single checkpoint."""
    candidates = []
    if os.path.isdir(root):
        if os.path.exists(os.path.join(root, MANIFEST_NAME)):
            candidates.append(root)
        for name in os.listdir(root):
            p = os.path.join(root, name)
            if os.path.isdir(p):
                candidates.append(p)
    candidates.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    for p in candidates:
        ok, _ = validate_checkpoint(p)
        if ok:
            return p
    return None
