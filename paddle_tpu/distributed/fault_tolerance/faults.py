"""Fault realizations that need framework imports (tensors, optimizer).

Kept out of plan.py so activating a plan from the env var never drags
jax/numpy into the rendezvous path's import graph.
"""
from __future__ import annotations

from .plan import fault_point

__all__ = ["install_grad_poison_hook", "poison_gradients"]

_installed = False


def poison_gradients(params, kind="nan"):
    """Overwrite the gradients of ``params`` with NaN (or Inf): the
    silent-corruption fault the skip-step path must catch."""
    import numpy as np
    import jax.numpy as jnp

    bad = np.nan if kind != "inf" else np.inf
    n = 0
    for p in params:
        g = getattr(p, "grad", None)
        if g is None:
            continue
        g._local_value_update(jnp.full(g._value.shape, bad, g._value.dtype))
        n += 1
    return n


def _pre_step_poison(optimizer, params):
    ev = fault_point("grad.poison")
    if ev is not None and params:
        poison_gradients(params[:1] if ev.arg == "first" else params,
                         kind=(ev.arg or "nan"))


def install_grad_poison_hook():
    """Register the ``grad.poison`` site on the optimizer's pre-step
    hook chain (idempotent; a no-op until a plan schedules the site)."""
    global _installed
    if _installed:
        return
    from ...optimizer.optimizer import register_pre_step_hook
    register_pre_step_hook(_pre_step_poison)
    _installed = True
