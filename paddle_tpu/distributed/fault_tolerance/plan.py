"""Deterministic, seeded fault injection (FaultPlan + inject()).

The robustness layer's core contract: every failure mode the distributed
stack must survive (dropped sockets, stalled heartbeats, killed workers,
torn checkpoints, NaN gradients) can be *replayed exactly*.  Production
code is instrumented with named ``fault_point(site)`` calls; a
``FaultPlan`` decides — deterministically, from explicit triggers or a
seeded RNG — whether that call fires a fault, and records every firing
in ``plan.history`` so two runs of the same plan produce byte-identical
failure sequences.

Site names are no longer ad-hoc strings: the module-level
``FAULT_SITES`` registry is the single source of truth for every
instrumented site (``<name>`` segments are wildcards for parameterized
families).  ``tpu_lint faults`` (analysis/fault_lint.py, TPU601/602)
statically audits every ``fault_point()`` / ``FaultPlan`` / ``inject()``
reference in the tree against it, and the chaos-schedule explorer
(fault_tolerance/chaos.py) enumerates it.

Activation: ``with inject(plan): ...`` or the ``PADDLE_TPU_FAULT_PLAN``
env var (JSON, or the compact ``site:action:k=v,...;site2:...`` form) so
a *relaunched* worker replays the same plan without code changes.
"""
from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time

from ... import observability as obs

__all__ = ["FaultEvent", "FaultPlan", "inject", "fault_point",
           "active_plan", "clear_active_plan", "InjectedFault",
           "InjectedConnectionError", "SimulatedWorkerDeath",
           "InjectedResourceExhausted", "ENV_FAULT_PLAN",
           "FAULT_SITES", "register_fault_site",
           "registered_fault_sites", "site_registered",
           "matching_sites"]

ENV_FAULT_PLAN = "PADDLE_TPU_FAULT_PLAN"

#: Central fault-site registry.  Keys are concrete site names or
#: ``<wildcard>`` patterns (one ``<name>`` segment matches exactly one
#: dot-separated segment); values are one-line descriptions of where the
#: site is instrumented.  A ``fault_point(site)`` / ``FaultPlan`` event
#: naming a site that matches nothing here can never fire — ``tpu_lint
#: faults`` flags it as TPU601.
FAULT_SITES = {
    "store.connect": "TCPStore client connect (distributed/store.py)",
    "store.<op>": "TCPStore client op: set/get/query/add/wait/"
                  "delete_key/num_keys (distributed/store.py)",
    "store.master_down": "ResilientStore: kill the live store master "
                         "(standby-promotion path, distributed/store.py)",
    "store.partition.<host>": "ClusterRouter: one host's view of the "
                              "store partitioned away (serving/cluster.py)",
    "heartbeat.beat": "ElasticManager heartbeat (fleet/elastic/manager.py)",
    "collective.<op>": "watchdog-wrapped collectives "
                       "(fault_tolerance/watchdog.py)",
    "checkpoint.write": "checkpoint shard write (checkpoint/save_load.py)",
    "checkpoint.commit": "checkpoint manifest commit "
                         "(checkpoint/save_load.py)",
    "grad.poison": "optimizer pre-step hook: NaN gradients "
                   "(fault_tolerance/faults.py)",
    "exec.oom": "executor/jit dispatch OOM probe (memory/guard.py)",
    "worker.step": "user training loops / smoke scripts",
    "serve.step_fail": "serving step dispatch (serving/engine.py)",
    "serve.step_hang": "serving step completion stall (watchdog target)",
    "serve.alloc_fail": "KV block allocation (serving/kv_cache.py)",
    "serve.import_fail": "KV block import seat (serving/kv_cache.py)",
    "serve.replica_down.<shard>": "per-replica step (serving/dp.py)",
    "serve.prefill_down.<engine>": "disaggregated prefill tier step "
                                   "(serving/disagg.py)",
    "serve.decode_down.<engine>": "disaggregated decode tier step "
                                  "(serving/disagg.py)",
    "kv.dma_fail": "host KV spill/promote DMA (serving/kv_cache.py)",
    "dist.device_lost.<rank>": "elastic trainer device-lost probe "
                               "(distributed/elastic_train.py)",
    "dist.host_preempt": "whole-host preemption notice "
                         "(distributed/elastic_train.py)",
    "elastic.snapshot.write": "async snapshot writer "
                              "(distributed/elastic_train.py)",
    "fabric.corrupt_payload": "in-flight fabric payload corruption "
                              "(serving/transport.py)",
    "fabric.host_down.<host>": "hard host death mid-step "
                               "(serving/cluster.py)",
    "fabric.preempt.<host>": "host preemption notice -> graceful drain "
                             "(serving/cluster.py)",
    "site.<name>": "reserved test-local namespace "
                   "(plan-mechanics unit tests)",
}


def register_fault_site(name, description=""):
    """Add a concrete site (or ``<wildcard>`` pattern) to the central
    registry; returns the name so callers can do
    ``SITE = register_fault_site("my.site", "...")``."""
    FAULT_SITES[str(name)] = str(description)
    return name


def registered_fault_sites():
    """A copy of the central registry: ``{site-or-pattern: description}``."""
    return dict(FAULT_SITES)


def _segment_matches(pat_seg, got_seg):
    if pat_seg.startswith("<") and pat_seg.endswith(">"):
        return True
    if "*" in got_seg:
        # a dynamic part discovered by static scan ("fabric.host_down.h*"
        # from an f-string) only proves the wildcard families, never a
        # literal segment
        return False
    return pat_seg == got_seg


def matching_sites(site):
    """All registry entries ``site`` matches.  ``site`` is a concrete
    name, or a scan form with ``*`` standing in for dynamic parts."""
    got = str(site).split(".")
    out = []
    for pat in FAULT_SITES:
        ps = pat.split(".")
        if len(ps) == len(got) and all(
                _segment_matches(p, g) for p, g in zip(ps, got)):
            out.append(pat)
    return out


def site_registered(site):
    """True when ``site`` matches at least one registry entry."""
    return bool(matching_sites(site))


class InjectedFault(Exception):
    """Marker base so handlers can tell injected faults from real ones."""


class InjectedConnectionError(ConnectionError, InjectedFault):
    """A dropped socket/op (subclass of ConnectionError so production
    retry paths treat it exactly like a real transient error)."""


class SimulatedWorkerDeath(RuntimeError, InjectedFault):
    """A simulated worker kill; escapes retry loops by design."""


class InjectedResourceExhausted(RuntimeError, InjectedFault):
    """A simulated device OOM.  The message contains RESOURCE_EXHAUSTED
    so the memory guard's detection path treats it exactly like a real
    XLA allocator failure (and the degradation ladder can be exercised
    on CPU)."""


_ACTIONS = ("drop", "delay", "stall", "kill", "corrupt", "nan", "oom")


class FaultEvent:
    """One scheduled fault: *site* + *action* + trigger.

    Trigger is either occurrence-based (fire on calls
    ``after <= n < after+count`` at the site) or probability-based
    (``prob`` drawn from the plan's seeded RNG — still deterministic
    for a fixed seed and call order).
    """

    def __init__(self, site, action, after=0, count=1, prob=None,
                 delay=0.0, arg=None):
        if action not in _ACTIONS:
            raise ValueError(
                f"FaultEvent: unknown action {action!r} (one of {_ACTIONS})")
        self.site = site
        self.action = action
        self.after = int(after)
        self.count = None if count in (None, "inf") else int(count)
        self.prob = None if prob is None else float(prob)
        self.delay = float(delay)
        self.arg = arg
        self.fired = 0

    def to_dict(self):
        return {"site": self.site, "action": self.action,
                "after": self.after, "count": self.count,
                "prob": self.prob, "delay": self.delay, "arg": self.arg}

    @classmethod
    def from_dict(cls, d):
        return cls(d["site"], d["action"], d.get("after", 0),
                   d.get("count", 1), d.get("prob"), d.get("delay", 0.0),
                   d.get("arg"))

    def __repr__(self):
        return (f"FaultEvent({self.site!r}, {self.action!r}, "
                f"after={self.after}, count={self.count}, "
                f"prob={self.prob}, delay={self.delay})")


class FaultPlan:
    """A seeded, replayable schedule of FaultEvents.

    ``history`` is the ground truth of what fired: a list of
    ``(site, action, occurrence_index)`` tuples.  The acceptance
    contract is that re-running the same plan against the same program
    yields an identical ``history``.
    """

    def __init__(self, events=None, seed=0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.events = list(events or [])
        self.history = []
        self._site_calls = {}
        self._lock = threading.Lock()

    # -- construction ----------------------------------------------------
    def add(self, site, action, **kwargs):
        self.events.append(FaultEvent(site, action, **kwargs))
        return self

    @classmethod
    def parse(cls, spec):
        """Parse JSON (``{"seed": 7, "events": [...]}``) or the compact
        form ``site:action[:k=v[,k=v...]][;site2:...]``  (optionally
        prefixed ``seed=N;``)."""
        spec = spec.strip()
        if spec.startswith("{"):
            d = json.loads(spec)
            return cls([FaultEvent.from_dict(e) for e in d.get("events", [])],
                       seed=d.get("seed", 0))
        seed = 0
        events = []
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if part.startswith("seed="):
                seed = int(part[5:])
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(f"FaultPlan.parse: bad event {part!r}")
            site, action = fields[0], fields[1]
            kwargs = {}
            if len(fields) > 2:
                for kv in filter(None, fields[2].split(",")):
                    k, _, v = kv.partition("=")
                    kwargs[k] = (None if v == "inf" and k == "count"
                                 else float(v) if k in ("prob", "delay")
                                 else int(v) if k in ("after", "count")
                                 else v)
            events.append(FaultEvent(site, action, **kwargs))
        return cls(events, seed=seed)

    def to_json(self):
        return json.dumps({"seed": self.seed,
                           "events": [e.to_dict() for e in self.events]})

    def reset(self):
        """Rewind for an identical replay: same seed, same triggers."""
        self.rng = random.Random(self.seed)
        self.history = []
        self._site_calls = {}
        for e in self.events:
            e.fired = 0
        return self

    # -- firing ----------------------------------------------------------
    def _match(self, site):
        n = self._site_calls[site] = self._site_calls.get(site, 0) + 1
        idx = n - 1  # occurrence index of THIS call
        for ev in self.events:
            if ev.site != site:
                continue
            if ev.prob is not None:
                # one RNG draw per (matching event, call): deterministic
                # for a fixed seed and call order
                if self.rng.random() < ev.prob and \
                        (ev.count is None or ev.fired < ev.count):
                    ev.fired += 1
                    return ev, idx
                continue
            if idx < ev.after:
                continue
            if ev.count is not None and ev.fired >= ev.count:
                continue
            ev.fired += 1
            return ev, idx
        return None, idx

    def fire(self, site, path=None):
        """Called by instrumented code.  Returns the fired FaultEvent
        (or None), after performing any centrally-realizable action:
        delay/stall sleep here; drop/kill raise; corrupt mangles
        ``path``; nan is realized by the caller (it owns the tensor)."""
        with self._lock:
            ev, idx = self._match(site)
            if ev is None:
                return None
            self.history.append((site, ev.action, idx))
        obs.instant("fault." + ev.action, cat="fault", site=site,
                    occurrence=idx)
        if ev.action in ("delay", "stall"):
            time.sleep(ev.delay)
        elif ev.action == "drop":
            raise InjectedConnectionError(
                f"fault-injection: dropped {site} (occurrence {idx})")
        elif ev.action == "kill":
            raise SimulatedWorkerDeath(
                f"fault-injection: worker killed at {site} "
                f"(occurrence {idx})")
        elif ev.action == "oom":
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: fault-injection: out of memory "
                f"at {site} (occurrence {idx})")
        elif ev.action == "corrupt" and path is not None:
            corrupt_file(path, seed=self.seed)
        return ev


def corrupt_file(path, seed=0):
    """Deterministically mangle a file in place (torn/bit-rotted write):
    flip a run of bytes at a seed-derived offset and truncate the tail."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        data = bytearray(b"\x00")
    rng = random.Random((seed, len(data)).__hash__())
    off = rng.randrange(len(data))
    for i in range(off, min(off + 16, len(data))):
        data[i] ^= 0xFF
    # torn write: drop the last quarter
    keep = max(1, (3 * len(data)) // 4)
    with open(path, "wb") as f:
        f.write(bytes(data[:keep]))


# -- global activation ---------------------------------------------------
_active = None
_env_checked = False
_state_lock = threading.Lock()


def active_plan():
    """The installed FaultPlan, else one parsed from
    ``PADDLE_TPU_FAULT_PLAN`` (checked once), else None."""
    global _active, _env_checked
    if _active is not None:
        return _active
    if not _env_checked:
        with _state_lock:
            if not _env_checked:
                _env_checked = True
                spec = os.environ.get(ENV_FAULT_PLAN)
                if spec:
                    _active = FaultPlan.parse(spec)
                    _install_hooks()
    return _active


def clear_active_plan():
    global _active, _env_checked
    _active = None
    _env_checked = False


def fault_point(site, path=None):
    """Instrumentation hook.  No-op (one global read) when no plan is
    active; otherwise lets the plan fire at this site."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.fire(site, path=path)


def _install_hooks():
    """Attach cross-layer hooks that need heavyweight imports (kept out
    of plan activation's critical path; idempotent, best effort)."""
    try:
        from .faults import install_grad_poison_hook
        install_grad_poison_hook()
    except Exception:
        pass


@contextlib.contextmanager
def inject(plan):
    """Activate ``plan`` for the dynamic extent of the block.

    The plan is reset on entry so each ``inject()`` run of the same plan
    replays the identical failure sequence."""
    global _active
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    plan.reset()
    prev = _active
    _active = plan
    _install_hooks()
    try:
        yield plan
    finally:
        _active = prev
