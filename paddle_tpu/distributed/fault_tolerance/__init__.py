"""Fault-tolerance subsystem (cross-cutting robustness layer).

Four pieces, each consumed by a different layer of the distributed
stack:

  plan.py      deterministic seeded fault injection — FaultPlan,
               inject(), fault_point() sites threaded through the
               store / heartbeat / collective / checkpoint / optimizer
               paths; replayable via PADDLE_TPU_FAULT_PLAN.
  watchdog.py  collective watchdog — bounded waits on the communication
               entry points with a which-ranks-checked-in diagnostic
               instead of an eternal hang.
  retry.py     exponential backoff with deterministic jitter + bounded
               retry_call; the TCPStore client's hardening primitives.
  atomic.py    crash-safe checkpoint primitives — atomic_write,
               checksum manifests, validate/latest-good scanning.
  chaos.py     seeded chaos-schedule explorer — enumerates the central
               FAULT_SITES registry, generates deterministic randomized
               fault schedules, replays each against a multi-host
               cluster on a synthetic bursty trace and checks a global
               invariant suite (exactly-once streams, zero leaked KV,
               bit-parity, no stale-epoch writes, bounded recovery).

See README.md §"Fault tolerance" for the env knobs.
"""
from .plan import (FaultEvent, FaultPlan, inject, fault_point, active_plan,
                   clear_active_plan, InjectedFault, InjectedConnectionError,
                   SimulatedWorkerDeath, InjectedResourceExhausted,
                   ENV_FAULT_PLAN, corrupt_file, FAULT_SITES,
                   register_fault_site, registered_fault_sites,
                   site_registered, matching_sites)
from .chaos import (ChaosSchedule, bursty_trace, generate_schedule,
                    serving_site_inventory, run_schedule, explore)
from .retry import (backoff_delays, retry_call, RetryExhausted,
                    RetryPolicy)
from .watchdog import (CollectiveWatchdog, CollectiveTimeoutError,
                       enable_watchdog, disable_watchdog, get_watchdog,
                       ENV_WATCHDOG_TIMEOUT)
from .atomic import (atomic_write, file_sha256, write_manifest,
                     validate_checkpoint, latest_good_checkpoint,
                     CheckpointCorruptionError, MANIFEST_NAME)
from .faults import poison_gradients

__all__ = [
    "FaultEvent", "FaultPlan", "inject", "fault_point", "active_plan",
    "clear_active_plan", "InjectedFault", "InjectedConnectionError",
    "SimulatedWorkerDeath", "InjectedResourceExhausted", "ENV_FAULT_PLAN",
    "corrupt_file",
    "backoff_delays", "retry_call", "RetryExhausted", "RetryPolicy",
    "CollectiveWatchdog", "CollectiveTimeoutError", "enable_watchdog",
    "disable_watchdog", "get_watchdog", "ENV_WATCHDOG_TIMEOUT",
    "atomic_write", "file_sha256", "write_manifest", "validate_checkpoint",
    "latest_good_checkpoint", "CheckpointCorruptionError", "MANIFEST_NAME",
    "poison_gradients",
    "FAULT_SITES", "register_fault_site", "registered_fault_sites",
    "site_registered", "matching_sites",
    "ChaosSchedule", "bursty_trace", "generate_schedule",
    "serving_site_inventory", "run_schedule", "explore",
]
