"""Seeded chaos-schedule explorer over the central fault-site registry.

Hand-written chaos drills (scripts/chaos_smoke.py) prove a handful of
curated failure stories; this module explores the space *systematically*
while keeping every run replayable:

  bursty_trace(seed, ...)       synthetic serving trace — heavy-tailed
                                (Pareto) arrival gaps + Zipf-shared
                                prompt prefixes, the scaled stand-in
                                for a millions-of-requests burst shape
  serving_site_inventory(...)   FAULT_SITES registry patterns expanded
                                to concrete injectable (site, actions)
                                pairs for an N-host cluster run
  generate_schedule(seed, ...)  seeded randomized fault schedule
                                (site x occurrence x duration); the
                                same seed reproduces the same schedule
                                byte-for-byte (ChaosSchedule.to_json)
  run_schedule(schedule, ...)   replay one schedule against a fresh
                                >=4-replica ClusterRouter over a
                                ResilientStore and check the global
                                invariant suite
  explore(...)                  N schedules end-to-end; one report

The invariant suite after EVERY schedule:
  * every request completes (zero lost, bounded steps — recovery time
    is bounded by construction, not by luck);
  * exactly-once stream delivery (contiguous indices, one terminal
    event, streamed tokens == the completion tail);
  * zero leaked KV blocks across tiers (HBM pools drained, no
    fabric payloads stranded in flight);
  * greedy/seeded bit-parity vs the fault-free run of the same trace
    (sampling keyed by fold_in(seed, absolute_position) makes every
    replay schedule-independent);
  * no stale-epoch write accepted: when the schedule killed the store
    master, a write carrying a pre-outage lease MUST be fenced with
    StoreEpochError after the run.

Heavy imports (serving, models, jax dispatch) stay function-local so
``paddle_tpu.distributed.fault_tolerance`` keeps importing light.
"""
from __future__ import annotations

import contextlib
import json
import random
import time

import numpy as np

from ... import observability as obs
from .plan import FaultPlan, inject, site_registered

__all__ = ["ChaosSchedule", "bursty_trace", "serving_site_inventory",
           "generate_schedule", "run_schedule", "explore"]


# ---------------------------------------------------------------------
# synthetic bursty trace
# ---------------------------------------------------------------------
def bursty_trace(seed, n_requests=8, vocab=97, prefix_pool=4,
                 prefix_len=16, tail_max=5, zipf_a=1.5, pareto_a=1.3,
                 max_new_tokens=6, horizon=24, arrival_rate=None,
                 duration=None, adapter_pool=0, adapter_zipf=1.3,
                 adapter_none_frac=0.25):
    """Deterministic synthetic serving trace.

    Arrival gaps are heavy-tailed (Pareto): most requests land in one
    burst, a few stragglers trickle in late — the shape that makes
    failover + replay interesting.  Prompts share prefixes drawn from
    a small pool with Zipf popularity (rank-k probability ~ k^-a), so
    prefix-affinity gossip routing has real structure to exploit.
    Returns ``[{"arrival_step", "prompt", "max_new_tokens"}, ...]``.

    Sustained-load mode: passing BOTH ``arrival_rate`` (requests per
    step) and ``duration`` (steps) replaces the Pareto burst with a
    steady open-loop arrival process — ``round(rate * duration)``
    requests at ``arrival_step = int(i / rate)`` — the soak shape for
    capacity drills (MoE expert-load churn under constant pressure)
    rather than failover drills.  ``n_requests`` is ignored and the
    horizon stretches to cover ``duration``.  Prompt construction (and
    its RNG draws) is identical in both modes; with the knob unset the
    output is byte-for-byte the historical trace for the same seed.

    Tenant mode: ``adapter_pool > 0`` tags each request with an
    ``"adapter"`` key — Zipf-popular ids ``"t0".."t{pool-1}"`` (rank-k
    probability ~ k^-adapter_zipf), with ``adapter_none_frac`` of the
    traffic left as base-model ``None`` rows — the mix the multi-LoRA
    store drills against.  The tags ride a SEPARATE RNG stream, so
    turning the pool on (or resizing it) never shifts the arrival /
    prompt draws, and with the knob at its 0 default the dicts are
    byte-for-byte the historical trace: no extra draws, no new key.
    """
    sustained = arrival_rate is not None and duration is not None
    if sustained:
        n_requests = max(1, int(round(float(arrival_rate)
                                      * float(duration))))
        horizon = max(int(horizon), int(duration))
    rng = np.random.RandomState(seed)
    prefixes = [[int(t) for t in rng.randint(1, vocab, size=prefix_len)]
                for _ in range(prefix_pool)]
    ranks = np.arange(1, prefix_pool + 1, dtype=np.float64) ** -zipf_a
    probs = ranks / ranks.sum()
    a_rng = a_probs = None
    if adapter_pool:
        # separate stream: tenant tags never perturb the prompt draws
        a_rng = np.random.RandomState([int(seed), 0xADA])
        a_ranks = np.arange(1, int(adapter_pool) + 1,
                            dtype=np.float64) ** -float(adapter_zipf)
        a_probs = a_ranks / a_ranks.sum()
    t = 0.0
    out = []
    for i in range(int(n_requests)):
        if sustained:
            t = i / float(arrival_rate)
        elif i:
            t += float(rng.pareto(pareto_a))
        p = int(rng.choice(prefix_pool, p=probs))
        tail = [int(x) for x in
                rng.randint(1, vocab, size=1 + int(rng.randint(tail_max)))]
        req = {"arrival_step": min(int(t), horizon - 1),
               "prompt": prefixes[p] + tail,
               "max_new_tokens": int(max_new_tokens)}
        if adapter_pool:
            base = a_rng.random_sample() < float(adapter_none_frac)
            aid = int(a_rng.choice(int(adapter_pool), p=a_probs))
            req["adapter"] = None if base else f"t{aid}"
        out.append(req)
    return out


# ---------------------------------------------------------------------
# site inventory + schedules
# ---------------------------------------------------------------------
#: Registry families the explorer may inject against a cluster run,
#: with the actions that are meaningful at each site.  ``{h}`` expands
#: per host.  Hard host removals (kill at host_down/preempt) are
#: bounded by the generator so a schedule can never take out the whole
#: cluster.
_SERVING_ACTIONS = (
    ("serve.step_fail", ("drop",)),
    ("serve.alloc_fail", ("oom",)),
    ("kv.dma_fail", ("drop",)),
    ("fabric.corrupt_payload", ("drop",)),
    ("store.get", ("drop", "delay")),
    ("store.set", ("drop",)),
    ("store.query", ("drop", "delay")),
    ("store.add", ("drop",)),
    ("store.master_down", ("kill",)),
    ("store.partition.h{h}", ("drop",)),
    ("fabric.host_down.h{h}", ("kill",)),
    ("fabric.preempt.h{h}", ("kill",)),
)

_REMOVAL_PREFIXES = ("fabric.host_down.", "fabric.preempt.")


def serving_site_inventory(hosts=4):
    """Concrete injectable ``(site, actions)`` pairs for a ``hosts``-
    replica cluster run, expanded from the central registry.  Every
    entry is validated against ``FAULT_SITES`` — the explorer can
    never schedule a typo'd site."""
    out = []
    for pat, actions in _SERVING_ACTIONS:
        if "{h}" in pat:
            out.extend((pat.format(h=h), actions)
                       for h in range(int(hosts)))
        else:
            out.append((pat, actions))
    for site, _ in out:
        if not site_registered(site):
            raise ValueError(
                f"chaos inventory site {site!r} is not in the central "
                "fault-site registry (fault_tolerance/plan.py)")
    return out


class ChaosSchedule:
    """One seeded fault schedule: an ordered list of
    ``{"site", "action", "after", "count", "delay"}`` entries plus the
    seed that generated it.  ``to_json()`` is canonical (sorted keys,
    no whitespace) so byte-for-byte reproducibility is testable."""

    def __init__(self, seed, entries):
        self.seed = int(seed)
        self.entries = list(entries)

    def sites(self):
        return sorted({e["site"] for e in self.entries})

    def to_json(self):
        return json.dumps({"seed": self.seed, "entries": self.entries},
                          sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, s):
        d = json.loads(s)
        return cls(d["seed"], d["entries"])

    def to_plan(self):
        plan = FaultPlan(seed=self.seed)
        for e in self.entries:
            kw = {"after": e["after"], "count": e["count"]}
            if e.get("delay"):
                kw["delay"] = e["delay"]
            plan.add(e["site"], e["action"], **kw)
        return plan

    def __repr__(self):
        return (f"ChaosSchedule(seed={self.seed}, "
                f"entries={len(self.entries)}, sites={self.sites()})")


def generate_schedule(seed, hosts=4, max_faults=4, horizon=20):
    """Seeded randomized schedule over the cluster site inventory.

    Determinism contract: driven entirely by ``random.Random(seed)``
    over a fixed inventory — the same (seed, hosts, max_faults,
    horizon) reproduces the same schedule byte-for-byte.  Safety
    bounds: at most ``hosts - 2`` distinct hosts may be hard-removed
    (host_down / preempt kills, one occurrence each) so survivors
    always exist, and the store master dies at most once per
    schedule."""
    rng = random.Random(seed)
    inv = serving_site_inventory(hosts)
    want = rng.randint(2, max(2, int(max_faults)))
    entries = []
    removed_hosts = set()
    master_downs = 0
    attempts = 0
    while len(entries) < want and attempts < 64:
        attempts += 1
        site, actions = inv[rng.randrange(len(inv))]
        if site.startswith(_REMOVAL_PREFIXES):
            h = site.rsplit(".", 1)[-1]
            if len(removed_hosts) >= max(0, int(hosts) - 2) \
                    or h in removed_hosts:
                continue
            removed_hosts.add(h)
            entries.append({"site": site, "action": "kill",
                            "after": rng.randint(1, max(1, horizon // 2)),
                            "count": 1, "delay": 0.0})
            continue
        if site == "store.master_down":
            if master_downs:
                continue
            master_downs += 1
            entries.append({"site": site, "action": "kill",
                            "after": rng.randint(0, horizon),
                            "count": 1, "delay": 0.0})
            continue
        action = actions[rng.randrange(len(actions))]
        entries.append({
            "site": site, "action": action,
            "after": rng.randint(0, horizon - 1),
            "count": rng.randint(1, 3),
            "delay": round(rng.uniform(0.01, 0.04), 3)
            if action == "delay" else 0.0})
    entries.sort(key=lambda e: (e["after"], e["site"], e["action"]))
    return ChaosSchedule(seed, entries)


# ---------------------------------------------------------------------
# replay + invariants
# ---------------------------------------------------------------------
def _default_model(seed=7):
    import paddle_tpu as paddle
    from ...models.gpt import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64)
    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _drive(model, trace, hosts=4, store=None, plan=None, sample=None,
           max_steps=600):
    """Run ``trace`` through a fresh ``hosts``-replica ClusterRouter
    (optionally under an injected fault plan) and collect outputs,
    stream events, and final stats.  ``ServingUnavailable`` from a
    step (every survivor mid-backoff) is absorbed — health probes
    re-admit hosts within a bounded number of steps."""
    from ...inference.serving import ClusterRouter
    from ...inference.serving.errors import ServingUnavailable

    sample = dict(sample or {})
    cl = ClusterRouter(model, hosts=hosts, store=store, num_blocks=64,
                       max_batch=4, block_size=8, max_model_len=64)
    events = {}
    try:
        queue = sorted(range(len(trace)),
                       key=lambda i: (trace[i]["arrival_step"], i))
        ids = {}
        streams = {}
        step = 0
        ctx = inject(plan) if plan is not None \
            else contextlib.nullcontext()
        with ctx:
            while queue or cl.has_unfinished():
                while queue and \
                        trace[queue[0]]["arrival_step"] <= step:
                    i = queue[0]
                    t = trace[i]
                    try:
                        rid = cl.add_request(
                            t["prompt"], request_id=f"chaos{i}",
                            max_new_tokens=t["max_new_tokens"],
                            **sample)
                    except ServingUnavailable:
                        break      # re-admit next step
                    queue.pop(0)
                    ids[i] = rid
                    streams[rid] = cl.open_stream(rid)
                try:
                    cl.step()
                except ServingUnavailable:
                    pass
                for rid, st in streams.items():
                    events.setdefault(rid, []).extend(st.drain())
                step += 1
                if step > max_steps:
                    raise RuntimeError(
                        f"no progress within {max_steps} steps: "
                        f"{len(queue)} unsubmitted, stats "
                        f"{cl.stats()}")
        for rid, st in streams.items():
            events.setdefault(rid, []).extend(st.drain())
        got = [cl.result(ids[i]) for i in range(len(trace))]
        stats = cl.stats()
    finally:
        cl.close()
    return got, stats, events, step


def _stream_violations(events, got, trace):
    """Exactly-once delivery check (the chaos_smoke contract): per
    request contiguous indices from 0, no duplicates, exactly one
    terminal event, streamed tokens == the completion tail.  Returns
    a list of violation strings (empty == clean)."""
    bad = []
    for k in range(len(trace)):
        rid = f"chaos{k}"
        evs = events.get(rid, [])
        toks = [(e.index, e.token) for e in evs if e.token is not None]
        idx = [i for i, _ in toks]
        if idx != list(range(len(idx))):
            bad.append(f"{rid}: stream indices {idx}")
        finals = [e for e in evs if e.finished]
        if len(finals) != 1:
            bad.append(f"{rid}: {len(finals)} terminal events")
        tail = got[k][len(trace[k]["prompt"]):]
        if [t for _, t in toks] != tail:
            bad.append(f"{rid}: streamed tokens diverge")
    return bad


def run_schedule(schedule, trace, model=None, hosts=4, sample=None,
                 reference=None, max_steps=600):
    """Replay ``schedule`` against a fresh cluster over a fresh
    :class:`~..store.ResilientStore` and check every global invariant.
    ``reference`` is the fault-free ``(outputs, steps)`` for the same
    (trace, sample); computed here when not supplied.  Returns a
    JSON-able report with ``ok`` plus per-invariant evidence."""
    from ..store import ResilientStore, StoreEpochError

    if model is None:
        model = _default_model()
    if reference is None:
        ref_got, _, ref_events, ref_steps = _drive(
            model, trace, hosts=hosts, sample=sample,
            max_steps=max_steps)
        reference = (ref_got, ref_steps)
    want, ref_steps = reference

    store = ResilientStore(timeout=1.0)
    pre_outage_lease = store.acquire_lease(owner="fenced-out-writer")
    t0 = time.perf_counter()
    failures = []
    try:
        got, stats, events, steps = _drive(
            model, trace, hosts=hosts, store=store,
            plan=schedule.to_plan(), sample=sample,
            max_steps=max_steps)
    except Exception as e:
        return {"ok": False, "seed": schedule.seed,
                "sites": schedule.sites(),
                "failures": [f"run died: {type(e).__name__}: {e}"],
                "wall_s": round(time.perf_counter() - t0, 3)}
    wall_s = time.perf_counter() - t0

    if len(got) != len(trace):
        failures.append(f"lost requests: {len(got)}/{len(trace)}")
    if got != want:
        failures.append("bit-parity: outputs diverge from the "
                        "fault-free run")
    failures.extend(_stream_violations(events, got, trace))
    # zero leaked KV: hard-killed hosts' pools are "gone HBM" (the
    # drill contract) — judge the survivors' pools plus the fabric
    killed = {e["site"].rsplit(".h", 1)[-1] for e in schedule.entries
              if e["site"].startswith("fabric.host_down.")}
    leaked = sum(h["blocks_in_use"]
                 for name, h in stats["per_host"].items()
                 if name[len("host"):] not in killed)
    if leaked != 0:
        failures.append(f"leaked {leaked} KV blocks on surviving "
                        "pools")
    if stats["fabric_in_flight"] != 0:
        failures.append(f"{stats['fabric_in_flight']} fabric payloads "
                        "stranded in flight")
    # epoch fencing: if the master died, the pre-outage lease MUST be
    # refused now — a fenced-out writer can never slip a write in
    fence_proven = None
    if store.promotions > 0:
        try:
            store.set("__chaos_fence_probe__", b"x",
                      lease=pre_outage_lease)
            fence_proven = False
            failures.append("stale-epoch write was ACCEPTED after "
                            "master promotion")
        except StoreEpochError:
            fence_proven = True
    # bounded recovery: the faulted run finished within the same step
    # budget; flag pathological blowups vs the fault-free run
    if steps > max(4 * ref_steps, ref_steps + 64):
        failures.append(f"recovery unbounded: {steps} steps vs "
                        f"{ref_steps} fault-free")
    store_stats = store.stats()
    store.close()
    return {"ok": not failures, "seed": schedule.seed,
            "sites": schedule.sites(), "schedule": schedule.to_json(),
            "failures": failures, "steps": steps,
            "ref_steps": ref_steps, "wall_s": round(wall_s, 3),
            "fence_proven": fence_proven,
            "store": store_stats,
            "degraded_ms": stats.get("degraded_ms", 0.0),
            "degraded_events": stats.get("degraded_events", 0),
            "failovers": stats.get("failovers", 0),
            "replays": stats.get("replays", 0),
            "preemptions": stats.get("preemptions", 0)}


def explore(seeds=range(8), hosts=4, n_requests=8, trace_seed=101,
            model=None, max_faults=4, log=None):
    """Replay one generated schedule per seed against the shared
    bursty trace, alternating greedy / seeded sampling so both decode
    paths face chaos.  The two fault-free references are computed once
    and shared across schedules.  Returns a soak report with per-
    schedule evidence, the distinct-site coverage set, and ``ok``."""
    if model is None:
        model = _default_model()
    trace = bursty_trace(trace_seed, n_requests=n_requests)
    seeded_kw = {"do_sample": True, "seed": 11, "top_k": 20,
                 "temperature": 0.8}
    refs = {}
    results = []
    for k, seed in enumerate(seeds):
        mode = "greedy" if k % 2 == 0 else "seeded"
        sample = {} if mode == "greedy" else seeded_kw
        if mode not in refs:
            ref_got, _, _, ref_steps = _drive(model, trace,
                                              hosts=hosts,
                                              sample=sample)
            refs[mode] = (ref_got, ref_steps)
        schedule = generate_schedule(seed, hosts=hosts,
                                     max_faults=max_faults)
        rep = run_schedule(schedule, trace, model=model, hosts=hosts,
                           sample=sample, reference=refs[mode])
        rep["mode"] = mode
        results.append(rep)
        if log is not None:
            log(f"schedule seed={seed} [{mode}] "
                f"ok={rep['ok']} sites={rep['sites']} "
                f"wall={rep.get('wall_s', 0):.1f}s")
    covered = sorted(set().union(*[set(r["sites"]) for r in results])) \
        if results else []
    report = {"ok": all(r["ok"] for r in results),
              "schedules": len(results),
              "distinct_sites": covered,
              "trace_seed": trace_seed, "hosts": hosts,
              "results": results}
    obs.instant("chaos.soak", cat="fault", schedules=len(results),
                sites=len(covered), ok=report["ok"])
    return report
