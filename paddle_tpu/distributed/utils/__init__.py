"""distributed.utils parity helpers."""
from __future__ import annotations

__all__ = ["get_world_size", "get_rank"]

from ..env import get_world_size, get_rank
