"""DataParallel.

Reference parity: `python/paddle/parallel.py` + `fluid/imperative/
reducer.cc` (gradient bucketing + fused allreduce) [UNVERIFIED — empty
reference mount].

TPU-native: with single-controller SPMD, DP is *sharding*, not message
passing (SURVEY.md §2.3): params stay replicated over the 'dp' mesh axis,
the input batch is sharded along it, and XLA inserts the gradient
all-reduce automatically when the VJP of a batch-sharded matmul hits a
replicated weight.  Gradient bucketing (reducer.cc) is unnecessary — XLA
fuses collectives.  `no_sync` marks grads to skip the sync (implemented by
keeping inputs unsharded in that window).
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import Layer
from .env import global_mesh, get_world_size

__all__ = ["DataParallel", "scale_loss"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self._sync_enabled = True
        mesh = global_mesh()
        self._mesh = mesh
        self._dp_axis = "dp" if "dp" in mesh.axis_names else \
            (mesh.axis_names[0] if mesh.axis_names else None)
        self._replicate_params()

    def _replicate_params(self):
        """Broadcast-equivalent: place every param replicated on the
        mesh.  Params already carrying a non-replicated sharding (mp
        layers, hand-sharded weights) keep their placement — blanket
        replication would silently clobber it."""
        if self._dp_axis is None or get_world_size() <= 1:
            return
        rep = NamedSharding(self._mesh, P())
        replicated = []
        for p in self._layers.parameters():
            sh = getattr(p._value, "sharding", None)
            if sh is not None and not sh.is_fully_replicated:
                continue
            try:
                p._value = jax.device_put(p._value, rep)
                replicated.append(p)
            except Exception:
                pass
        self._sync_replicated_params(replicated)

    def _sync_replicated_params(self, params):
        """Hook: TensorParallel aligns replicated params across
        processes here; single-process DataParallel needs nothing."""

    def _shard_input(self, t):
        if not isinstance(t, Tensor) or self._dp_axis is None or \
                get_world_size() <= 1 or not self._sync_enabled:
            return t
        shape = t._value.shape
        n = self._mesh.shape[self._dp_axis]
        if not shape or shape[0] % n != 0:
            return t
        sh = NamedSharding(self._mesh,
                           P(self._dp_axis, *([None] * (len(shape) - 1))))
        try:
            return Tensor(jax.device_put(t._value, sh), _internal=True,
                          stop_gradient=t.stop_gradient)
        except Exception:
            return t

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(i) for i in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._sync_enabled
        self._sync_enabled = False
        try:
            yield
        finally:
            self._sync_enabled = prev

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass  # XLA already reduced grads over the dp axis

    # passthrough
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self


def scale_loss(loss):
    return loss
