"""Process launcher: `python -m paddle_tpu.distributed.launch`.

Reference parity: `python/paddle/distributed/launch/` (`main.py`,
`controllers/collective.py`) — builds a Pod of worker Containers, assigns
PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS env, spawns + monitors the
processes, tees per-rank logs, tears the pod down on failure [UNVERIFIED
— empty reference mount; SURVEY.md §3.5].

TPU-native: jax is a multi-controller runtime — ONE process per host
drives all local chips (the reference runs one process per GPU).  So the
default nproc_per_node is 1, the rendezvous is jax.distributed's
coordination service (reached via MASTER_ADDR / --master; the reference
uses its TCPStore), and `init_parallel_env` inside the worker performs
the actual `jax.distributed.initialize`.  nproc_per_node > 1 is
supported for CPU-backend simulation of a multi-host pod on localhost
(the test strategy of SURVEY.md §4: fake-cluster-on-localhost).
"""
from __future__ import annotations

import argparse
import os
import signal
import struct
import subprocess
import sys
import time

__all__ = ["main", "launch"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (one controller "
                    "process per host on TPU)")
    p.add_argument("--master", default=None,
                   help="coordinator endpoint host:port (rank-0 host)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 on TPU; >1 for CPU "
                        "fake-cluster tests)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTARTS",
                                              "0")),
                   help="elastic: relaunch the pod up to N times on "
                        "worker failure (training resumes from user "
                        "checkpoints)")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI compat (device "
                        "visibility is PJRT-managed on TPU)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(args):
    """Elastic outer loop (reference: ElasticManager relaunch): run the
    pod; on failure relaunch up to --max_restarts times with
    PADDLE_RESTART_CNT incremented so workers resume from checkpoints.
    With --nnodes > 1 the relaunch decision is COORDINATED across the
    per-node launchers through a TCPStore epoch counter (see
    _launch_multihost_elastic)."""
    if args.nnodes > 1:
        return _launch_multihost_elastic(args)
    restarts = 0
    while True:
        rc = _launch_once(args, restarts)
        if rc == 0 or rc == 130 or restarts >= args.max_restarts:
            return rc  # 130 = user interrupt: never relaunch on Ctrl-C
        restarts += 1
        print(f"launch: elastic relaunch {restarts}/{args.max_restarts} "
              f"(previous rc={rc})", file=sys.stderr, flush=True)


def _master_of(args):
    master = args.master or os.environ.get("MASTER_ADDR", "127.0.0.1")
    if ":" in master:
        addr, port = master.rsplit(":", 1)
    else:
        addr, port = master, os.environ.get("MASTER_PORT", "8476")
    return addr, int(port)


def _launch_multihost_elastic(args):
    """Cross-host elastic pod (reference: ElasticManager's etcd watch —
    `fleet/elastic/manager.py` [UNVERIFIED — empty reference mount;
    SURVEY.md §2.3 elastic row, §5 failure detection]).

    jax.distributed cannot re-admit a single rank into a live
    coordination service, so — like the reference pod — the restart
    unit is the WHOLE pod.  The per-node launchers coordinate through a
    TCPStore (served by the node-0 launcher on master_port+797):

      * any local worker death bumps the shared ``epoch`` counter;
      * every launcher polls ``epoch``; a bump (local or remote) tears
        down the local workers — which are typically HUNG in a
        collective whose peer died, the NCCL-hang analogue — and
        relaunches them with PADDLE_RESTART_CNT=epoch;
      * when ``epoch`` exceeds --max_restarts the observing launcher
        flags ``abort`` and every node exits non-zero;
      * launchers sync at an epoch barrier so a relaunched rank 0 has
        released the coordinator port before peers redial it.
    """
    from ..store import TCPStore
    addr, port = _master_of(args)
    store = TCPStore(addr, port + 797,
                     is_master=(args.node_rank == 0),
                     world_size=args.nnodes, timeout=120)
    epoch = 0
    rc = 0
    while True:
        procs, logs = _spawn_pod(args, epoch)
        try:
            rc, peer_bump = _watch_pod(args, procs, store, epoch)
        except KeyboardInterrupt:
            for pr in procs:
                pr.send_signal(signal.SIGINT)
            return 130
        finally:
            for lf in logs:
                lf.close()
        if rc == 0 and not peer_bump:
            # clean completion: node 0 hosts the store server, so it
            # must outlive every peer's LAST store poll — wait until
            # all nodes have checked in done before returning
            try:
                store.add("done", 1)
                if args.node_rank == 0:
                    deadline = time.time() + 120
                    while store.add("done", 0) < args.nnodes:
                        if time.time() > deadline:
                            break
                        time.sleep(0.1)
            except Exception:
                pass
            return 0
        try:
            if rc != 0:
                # first-failure-wins: k simultaneous node failures in
                # one round must consume ONE restart, not k, and every
                # node must read the same next epoch for its barrier
                if store.add(f"bump{epoch}", 1) == 1:
                    store.add("epoch", 1)
            cur = int(store.add("epoch", 0))
            if cur > args.max_restarts:
                store.set("abort", b"1")
                print(f"launch: elastic budget exhausted "
                      f"(epoch {cur} > max_restarts "
                      f"{args.max_restarts}); aborting pod",
                      file=sys.stderr, flush=True)
                return rc or 1
            if store.query("abort") is not None:
                return rc or 1
            if store.add("done", 0) > 0:
                # a peer already finished and exited: the pod can never
                # be reformed at full world size — abort, don't wait
                print("launch: a peer node completed before this "
                      "failure; pod cannot be reformed — aborting",
                      file=sys.stderr, flush=True)
                store.set("abort", b"1")
                return rc or 1
            # epoch barrier that cannot deadlock on a finished peer:
            # wait until every node has either arrived or checked in
            # done (a done peer makes reforming impossible -> abort)
            store.add(f"arrive{cur}", 1)
            deadline = time.time() + 120
            while True:
                arrived = store.add(f"arrive{cur}", 0)
                # abort/done wins over a formed barrier: a timed-out
                # peer's arrival is never retracted, so the count alone
                # must not admit us into a pod that can never form
                if store.add("done", 0) > 0 \
                        or store.query("abort") is not None:
                    print("launch: pod cannot be reformed "
                          "(peer done/aborted); exiting",
                          file=sys.stderr, flush=True)
                    store.set("abort", b"1")
                    return rc or 1
                if arrived >= args.nnodes:
                    break
                if time.time() > deadline:
                    print("launch: epoch barrier timed out; aborting",
                          file=sys.stderr, flush=True)
                    store.set("abort", b"1")
                    return rc or 1
                time.sleep(0.05)
        except Exception as e:
            # store gone = a peer launcher aborted and took the server
            print(f"launch: elastic store lost ({e}); aborting",
                  file=sys.stderr, flush=True)
            return rc or 1
        print(f"launch: elastic relaunch -> epoch {cur} "
              f"(node {args.node_rank})", file=sys.stderr, flush=True)
        epoch = cur


def _watch_pod(args, procs, store, epoch):
    """Returns (rc, peer_bump).  Kills local workers on either a local
    failure or (store is not None) a remote epoch bump / abort flag.
    Shared by the single-node path (store=None) and the multi-host
    elastic loop — one watch loop, one teardown escalation."""
    nproc = args.nproc_per_node
    alive = set(range(nproc))
    rc = 0
    peer_bump = False
    last_poll = 0.0
    while alive:
        for i in list(alive):
            r = procs[i].poll()
            if r is None:
                continue
            alive.discard(i)
            if r != 0:
                rc = r
                print(f"launch: rank {args.node_rank * nproc + i} "
                      f"exited rc={r}; terminating local pod",
                      file=sys.stderr, flush=True)
                _teardown(procs, alive)
                return rc, peer_bump
        now = time.time()
        if store is not None and now - last_poll >= 0.5:
            last_poll = now
            try:
                if store.query("abort") is not None:
                    _teardown(procs, alive)
                    return 1, True
                cur = store.query("epoch")
                if cur is not None and len(cur) == 8 and \
                        struct.unpack("<q", cur)[0] > epoch:
                    print(f"launch: node {args.node_rank} observed "
                          f"remote epoch bump; terminating local pod",
                          file=sys.stderr, flush=True)
                    _teardown(procs, alive)
                    return rc, True
            except Exception:
                _teardown(procs, alive)
                return 1, True
        time.sleep(0.1)
    return rc, peer_bump


def _teardown(procs, alive):
    for j in list(alive):
        procs[j].terminate()
    deadline = time.time() + 10
    for j in list(alive):
        while procs[j].poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if procs[j].poll() is None:
            procs[j].kill()
    alive.clear()


def _spawn_pod(args, restarts=0):
    """Spawn this node's worker processes; returns (procs, log files)."""
    nproc = args.nproc_per_node
    world = args.nnodes * nproc
    addr, port = _master_of(args)

    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    logs = []
    for local_rank in range(nproc):
        rank = args.node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_NNODES": str(args.nnodes),
            "MASTER_ADDR": addr,
            "MASTER_PORT": str(port),
            "PADDLE_CURRENT_ENDPOINT": f"{addr}:{int(port) + rank + 1}",
            "PADDLE_RESTART_CNT": str(restarts),
        })
        suffix = f".restart{restarts}" if restarts else ""
        log_path = os.path.join(args.log_dir, f"workerlog.{rank}{suffix}")
        lf = open(log_path, "w")
        logs.append(lf)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        procs.append(subprocess.Popen(cmd, env=env, stdout=lf,
                                      stderr=subprocess.STDOUT))
        print(f"launch: rank {rank} pid {procs[-1].pid} -> {log_path}",
              flush=True)
    return procs, logs


def _launch_once(args, restarts=0):
    procs, logs = _spawn_pod(args, restarts)

    # watch loop (reference: CollectiveController.watch): first failure
    # tears down the pod
    try:
        rc, _ = _watch_pod(args, procs, store=None, epoch=restarts)
    except KeyboardInterrupt:
        for pr in procs:
            pr.send_signal(signal.SIGINT)
        rc = 130
    finally:
        for lf in logs:
            lf.close()
    return rc


def main(argv=None):
    sys.exit(launch(_parse_args(argv)))
