"""Process launcher: `python -m paddle_tpu.distributed.launch`.

Reference parity: `python/paddle/distributed/launch/` (`main.py`,
`controllers/collective.py`) — builds a Pod of worker Containers, assigns
PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS env, spawns + monitors the
processes, tees per-rank logs, tears the pod down on failure [UNVERIFIED
— empty reference mount; SURVEY.md §3.5].

TPU-native: jax is a multi-controller runtime — ONE process per host
drives all local chips (the reference runs one process per GPU).  So the
default nproc_per_node is 1, the rendezvous is jax.distributed's
coordination service (reached via MASTER_ADDR / --master; the reference
uses its TCPStore), and `init_parallel_env` inside the worker performs
the actual `jax.distributed.initialize`.  nproc_per_node > 1 is
supported for CPU-backend simulation of a multi-host pod on localhost
(the test strategy of SURVEY.md §4: fake-cluster-on-localhost).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["main", "launch"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (one controller "
                    "process per host on TPU)")
    p.add_argument("--master", default=None,
                   help="coordinator endpoint host:port (rank-0 host)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 on TPU; >1 for CPU "
                        "fake-cluster tests)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTARTS",
                                              "0")),
                   help="elastic: relaunch the pod up to N times on "
                        "worker failure (training resumes from user "
                        "checkpoints)")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI compat (device "
                        "visibility is PJRT-managed on TPU)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(args):
    """Elastic outer loop (reference: ElasticManager relaunch): run the
    pod; on failure relaunch up to --max_restarts times with
    PADDLE_RESTART_CNT incremented so workers resume from checkpoints."""
    restarts = 0
    while True:
        rc = _launch_once(args, restarts)
        if rc == 0 or rc == 130 or restarts >= args.max_restarts:
            return rc  # 130 = user interrupt: never relaunch on Ctrl-C
        restarts += 1
        print(f"launch: elastic relaunch {restarts}/{args.max_restarts} "
              f"(previous rc={rc})", file=sys.stderr, flush=True)


def _launch_once(args, restarts=0):
    nproc = args.nproc_per_node
    world = args.nnodes * nproc
    master = args.master or os.environ.get("MASTER_ADDR", "127.0.0.1")
    if ":" in master:
        addr, port = master.rsplit(":", 1)
    else:
        addr, port = master, os.environ.get("MASTER_PORT", "8476")

    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    logs = []
    for local_rank in range(nproc):
        rank = args.node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_NNODES": str(args.nnodes),
            "MASTER_ADDR": addr,
            "MASTER_PORT": str(port),
            "PADDLE_CURRENT_ENDPOINT": f"{addr}:{int(port) + rank + 1}",
            "PADDLE_RESTART_CNT": str(restarts),
        })
        suffix = f".restart{restarts}" if restarts else ""
        log_path = os.path.join(args.log_dir, f"workerlog.{rank}{suffix}")
        lf = open(log_path, "w")
        logs.append(lf)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        procs.append(subprocess.Popen(cmd, env=env, stdout=lf,
                                      stderr=subprocess.STDOUT))
        print(f"launch: rank {rank} pid {procs[-1].pid} -> {log_path}",
              flush=True)

    # watch loop (reference: CollectiveController.watch): first failure
    # tears down the pod
    rc = 0
    try:
        alive = set(range(nproc))
        while alive:
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    rc = r
                    print(f"launch: rank {args.node_rank * nproc + i} "
                          f"exited rc={r}; terminating pod",
                          file=sys.stderr, flush=True)
                    for j in alive:
                        procs[j].terminate()
                    alive.clear()
                    break
            time.sleep(0.2)
    except KeyboardInterrupt:
        for pr in procs:
            pr.send_signal(signal.SIGINT)
        rc = 130
    finally:
        for lf in logs:
            lf.close()
    return rc


def main(argv=None):
    sys.exit(launch(_parse_args(argv)))
