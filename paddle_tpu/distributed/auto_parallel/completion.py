"""Sharding completion: propagate dist attrs through a whole function.

Role of the reference's completion pass
(`auto_parallel/static/completion.py`: walk the serial program and
infer each op's dist attrs from its inputs' [UNVERIFIED — empty
reference mount]).

TPU-native: XLA's sharding propagation IS the completion algorithm, and
it runs on the whole computation during compilation — strictly more
ops, more accurately, than a per-op rule table.  This module exposes it:
`complete(fn, mesh, in_specs, *avals)` compiles fn with the given input
shardings and returns the shardings XLA chose for every output (and,
via `propagate_intermediate`, for any intermediate you mark with
`mark_sharding`).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["complete", "mark_sharding", "spec_of"]


def mark_sharding(x, mesh, entries):
    """In-graph annotation (`shard_tensor` for traced values): a
    sharding constraint XLA must honor and propagate from."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*entries)))


def spec_of(sharding) -> tuple:
    """PartitionSpec entries of a (Named)Sharding, () for replicated."""
    spec = getattr(sharding, "spec", None)
    return tuple(spec) if spec is not None else ()


def complete(fn, mesh, in_specs, *avals):
    """Compile `fn` with inputs placed per `in_specs` and return
    ``(out_shardings, compiled)`` — the completed placement of every
    output.  `in_specs` entries are PartitionSpec entry lists (or None
    for replicated); `avals` are ShapeDtypeStructs or arrays."""
    shardings = tuple(
        NamedSharding(mesh, P(*(s or ()))) for s in in_specs)
    jitted = jax.jit(fn, in_shardings=shardings)
    compiled = jitted.lower(*avals).compile()
    outs = compiled.output_shardings
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    return [spec_of(s) for s in outs], compiled
