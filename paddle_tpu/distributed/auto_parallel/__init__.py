from . import api
from .api import (ProcessMesh, shard_tensor, shard_op, Shard, Replicate,
                  Partial, reshard, dtensor_from_fn, shard_layer)
from . import completion
from . import cost_model
from . import engine
from . import sharding
from .sharding import (MeshPlan, annotate_params, get_mesh_plan,
                       match_partition_rules, set_mesh_plan)
from . import overlap
from .overlap import (overlap_report, select_mode, sharded_matmul,
                      tile_arithmetic)
from . import pipeline
from .pipeline import PipelineSchedule, one_f_one_b_order
from .cost_model import Planner, estimate_cost, comm_cost_seconds
from .engine import Strategy, DistModel, Engine, to_static
