from . import api
from .api import (ProcessMesh, shard_tensor, shard_op, Shard, Replicate,
                  Partial, reshard, dtensor_from_fn, shard_layer)
