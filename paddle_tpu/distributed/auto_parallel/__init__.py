from . import api
from .api import (ProcessMesh, shard_tensor, shard_op, Shard, Replicate,
                  Partial, reshard, dtensor_from_fn, shard_layer)
from . import completion
from . import cost_model
from . import engine
from .cost_model import Planner, estimate_cost, comm_cost_seconds
from .engine import Strategy, DistModel, Engine, to_static
