"""Tile-level compute/communication overlap for sharded matmuls.

ROADMAP item 1 / PAPERS.md "Tile-Level Activation Overlap" (arxiv
2607.02521): a tensor-parallel matmul that waits for its collective
leaves the MXU idle for the whole interconnect transfer.  This module
decomposes both TP matmul directions into per-tile ring steps inside
``shard_map`` — the same discipline as ``ops/ring_flash_attention.py`` —
so each ``ppermute`` hop is issued *before* the partial dot it does not
depend on and XLA's scheduler runs the transfer under the compute:

* **all-gather-matmul** (column-parallel input side): ``a`` is
  row-sharded over the axis, ``b`` replicated.  Each step rotates the
  resident ``a``-shard one hop while the current shard's partial dot
  lands in its output block (``out = AG(a) @ b``, replicated).
* **matmul-reduce-scatter** (row-parallel dual): ``a`` column-sharded,
  ``b`` row-sharded.  A row-tile accumulator travels the ring the
  opposite way; each step's hop carries the running partial sum while
  the next tile's dot computes (``out = RS(a @ b)``, row-scattered).

Both have a **sequential fallback** (collective completes strictly
before any compute) that is *bit-exact* against the overlapped path:

* AG direction: row-blocked dots are bit-identical to the gathered full
  dot per output row, so ``all_gather`` + one dot matches exactly.
* RS direction: the fallback reduces the full local product through a
  manual ring reduce-scatter with the **same accumulation order** as the
  overlapped schedule; tile slices of the full product are bit-equal to
  per-tile dots, so the two paths add identical summands identically.

Selection is ``pallas_gate``-style: ``PADDLE_TPU_OVERLAP``
(auto|overlap|sequential) plus a cached probe compile per mesh topology,
consulted by ``select_mode`` — the static Executor and
``MeshPlan.wrap_step`` callers pick overlapped vs sequential per step
function, and the chosen mode is part of ``plan_cache_token`` so an env
flip never reuses a stale executable.

``measured_sharded_matmul`` drives the same ring step-wise from the
host, emitting ``cat="collective"`` spans (with the axis attr the eager
collectives use) whose lifetime genuinely brackets the in-flight
``ppermute`` — overlapped mode dispatches the partial dot inside that
window, sequential mode blocks first — so the per-axis overlap ratio in
``observability.phase_breakdown()`` comes from real timeline spans.
"""
from __future__ import annotations

import logging
import math
import os
import traceback

import numpy as np

from ... import observability as obs

__all__ = [
    "ENV_OVERLAP", "OverlapProbeResult", "all_gather_matmul_local",
    "executor_linear_override", "matmul_reduce_scatter_local",
    "measured_sharded_matmul", "mode_token", "overlap_eligible",
    "overlap_flag", "overlap_report", "probe_overlap",
    "reset_overlap_cache", "select_mode", "sharded_matmul",
    "tile_arithmetic",
]

ENV_OVERLAP = "PADDLE_TPU_OVERLAP"

_logger = logging.getLogger("paddle_tpu.overlap")

#: (axis, axis_sizes) -> OverlapProbeResult, cleared by reset
_probe_results: dict = {}
#: (plan token, axis, direction, mode, shapes/dtypes) -> compiled fn
_jit_cache: dict = {}


def _jnp():
    import jax.numpy as jnp
    return jnp


def overlap_flag():
    """Normalized ``PADDLE_TPU_OVERLAP``: auto | overlap | sequential."""
    raw = os.environ.get(ENV_OVERLAP, "auto").strip().lower()
    if raw in ("", "auto"):
        return "auto"
    if raw in ("1", "on", "true", "overlap"):
        return "overlap"
    if raw in ("0", "off", "false", "sequential", "seq"):
        return "sequential"
    raise ValueError(
        f"bad {ENV_OVERLAP}={raw!r}; expected auto|overlap|sequential")


def mode_token():
    """Cache-token component for the *configured* overlap mode.

    The probe outcome is deterministic per process+mesh, so only the
    env-level configuration needs to key executable caches (MIGRATION:
    mesh cache tokens include the overlap mode).
    """
    return overlap_flag()


# ---------------------------------------------------------------------------
# Probe / selection (pallas_gate discipline)
# ---------------------------------------------------------------------------

class OverlapProbeResult:
    """Outcome of one overlap probe compile on a concrete mesh."""

    __slots__ = ("key", "ok", "error", "error_type")

    def __init__(self, key, ok, error=None, error_type=None):
        self.key = key
        self.ok = ok
        self.error = error
        self.error_type = error_type

    def to_dict(self):
        d = {"mesh": dict(self.key[1]), "axis": self.key[0],
             "ok": self.ok, "probed": True}
        if not self.ok:
            d["error"] = self.error
            d["error_type"] = self.error_type
        return d


def _probe_key(plan, axis):
    return (axis, tuple(plan.axis_sizes.items()))


def _run_probe(plan, axis):
    """Compile+run both directions at a tiny shape on the plan's mesh
    and check the overlapped path against its sequential fallback."""
    from ...analysis.diagnostics import Diagnostic, record
    jnp = _jnp()
    key = _probe_key(plan, axis)
    P = plan.axis_size(axis)
    try:
        m, k, n = 4 * P, 8, 8
        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        for direction in ("ag", "rs"):
            o = sharded_matmul(a, b, plan=plan, axis=axis,
                               direction=direction, mode="overlap")
            s = sharded_matmul(a, b, plan=plan, axis=axis,
                               direction=direction, mode="sequential")
            if not bool(jnp.all(o == s)):
                raise AssertionError(
                    f"overlapped {direction} diverged from the "
                    f"sequential fallback at the probe shape")
        result = OverlapProbeResult(key, True)
        _logger.info("overlap probe OK on mesh %s axis %s",
                     plan.describe(), axis)
    except Exception as exc:
        err = "".join(traceback.format_exception_only(type(exc), exc))
        err = err.strip()
        record(Diagnostic(
            "TPU110",
            f"overlapped sharded matmul failed its probe compile on "
            f"mesh {plan.describe()} ({type(exc).__name__}); step "
            f"functions fall back to the sequential collective-then-dot "
            f"path",
            site=f"overlap_gate[{plan.describe()}/{axis}]",
            hint=f"overlap_report() carries the full error; set "
                 f"{ENV_OVERLAP}=sequential to silence the probe",
            data={"error": err[:2000]}))
        result = OverlapProbeResult(key, False, error=err,
                                    error_type=type(exc).__name__)
        _logger.exception(
            "overlap probe FAILED on mesh %s axis %s; falling back to "
            "sequential collectives for this process", plan.describe(),
            axis)
    _probe_results[key] = result
    return result


def probe_overlap(plan, axis="tp", force=False):
    """Probe (cached) the overlapped path on ``plan``'s mesh."""
    key = _probe_key(plan, axis)
    if not force and plan.axis_size(axis) <= 1:
        return OverlapProbeResult(key, False,
                                  error=f"axis {axis!r} has size <= 1",
                                  error_type="skipped")
    result = _probe_results.get(key)
    if result is None:
        result = _run_probe(plan, axis)
    return result


def select_mode(plan, axis="tp"):
    """Per-step-function selection: ``'overlap'`` or ``'sequential'``.

    ``sequential`` when the flag forces it, there is no plan / a
    virtual plan / no >1-sized ``axis``; ``overlap`` when the flag
    forces it; under ``auto`` the cached probe decides.
    """
    flag = overlap_flag()
    if flag == "sequential":
        return "sequential"
    if plan is None or plan.is_virtual or plan.axis_size(axis) <= 1:
        return "sequential"
    if flag == "overlap":
        return "overlap"
    return "overlap" if probe_overlap(plan, axis).ok else "sequential"


def overlap_report():
    """Cached probe outcomes keyed ``'<mesh>/<axis>'``."""
    return {f"{dict(key[1])}/{key[0]}": res.to_dict()
            for key, res in _probe_results.items()}


def reset_overlap_cache():
    _probe_results.clear()
    _jit_cache.clear()


# ---------------------------------------------------------------------------
# Eligibility arithmetic (shared with the TPU504 audit)
# ---------------------------------------------------------------------------

def overlap_eligible(dim, axis_size):
    """A dimension tiles cleanly iff it divides by the tile count
    (= axis size); a ragged last tile forces padded transfers."""
    return int(axis_size) > 1 and int(dim) % int(axis_size) == 0


def tile_arithmetic(dim, axis_size):
    """Human-readable tile math for diagnostics."""
    dim, P = int(dim), int(axis_size)
    if P <= 1:
        return f"{dim} rows, 1 tile (axis size {P}: nothing to overlap)"
    if dim % P == 0:
        return f"{dim} % {P} == 0 -> {P} tiles of {dim // P}"
    pad = ((dim + P - 1) // P) * P
    return (f"{dim} % {P} == {dim % P} -> last tile ragged "
            f"({dim - (P - 1) * ((dim + P - 1) // P)} of "
            f"{(dim + P - 1) // P} rows); pad to {pad}")


# ---------------------------------------------------------------------------
# Per-shard ring schedules (call inside shard_map)
# ---------------------------------------------------------------------------

def _dot(x, w):
    """Partial-tile dot.  bf16 inputs accumulate in f32 (cast back at
    the end of the schedule) so tile count never changes the precision
    story; f32 stays plain so bit-exactness claims are about schedule
    order only."""
    jnp = _jnp()
    if x.dtype == jnp.bfloat16 or w.dtype == jnp.bfloat16:
        return jnp.matmul(x, w, preferred_element_type=jnp.float32)
    return jnp.matmul(x, w)


def _out_dtype(a, b):
    return _jnp().promote_types(a.dtype, b.dtype)


def _acc_dtype(a, b):
    """Dtype the ring accumulates in (f32 for bf16 inputs)."""
    jnp = _jnp()
    if a.dtype == jnp.bfloat16 or b.dtype == jnp.bfloat16:
        return jnp.float32
    return _out_dtype(a, b)


def all_gather_matmul_local(a, b, *, axis, axis_size, mode="overlap"):
    """Per-shard ``all_gather(a) @ b``: ``a`` = [m_local, k] (dim 0
    sharded over ``axis``), ``b`` = [k, n] replicated.  Returns the
    full [m, n] product on every shard.

    Overlapped: each step issues the next shard's ``ppermute`` hop
    *before* the resident shard's partial dot — the two are
    independent, so the transfer runs under the MXU.  Sequential:
    the whole gather completes, then one dot (bit-exact vs overlapped:
    row-blocked dots are per-row identical to the full dot).
    """
    import jax
    jnp = _jnp()
    P = int(axis_size)
    if mode == "sequential" or P <= 1:
        a_full = jax.lax.all_gather(a, axis, axis=0, tiled=True) \
            if P > 1 else a
        return _dot(a_full, b).astype(_out_dtype(a, b))
    m_local = a.shape[0]
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % P) for i in range(P)]
    out = jnp.zeros((m_local * P, b.shape[-1]), _acc_dtype(a, b))
    a_cur = a
    for r in range(P):
        # hop first: independent of this step's dot -> XLA overlaps
        a_nxt = jax.lax.ppermute(a_cur, axis, perm) if r < P - 1 else None
        partial = _dot(a_cur, b)
        src = (me - r) % P          # original owner of the resident shard
        start = src * m_local
        out = jax.lax.dynamic_update_slice(
            out, partial, (start, jnp.zeros((), start.dtype)))
        a_cur = a_nxt
    return out.astype(_out_dtype(a, b))


def matmul_reduce_scatter_local(a, b, *, axis, axis_size,
                                mode="overlap"):
    """Per-shard ``reduce_scatter(a @ b)``: ``a`` = [m, k_local]
    (contraction dim sharded over ``axis``), ``b`` = [k_local, n].
    Returns this shard's [m // axis_size, n] row tile of the summed
    product.

    Overlapped: a row-tile accumulator rides the ring (device ``i`` ->
    ``i-1``); each step's hop carries the running sum while the next
    tile's partial dot computes.  Sequential: the full local product
    completes first, then a manual ring reduce-scatter with the *same*
    accumulation order — tile slices of the full product are bit-equal
    to per-tile dots, so the two modes are bit-exact f32.
    """
    import jax
    P = int(axis_size)
    dt = _out_dtype(a, b)
    if P <= 1:
        return _dot(a, b).astype(dt)
    m_local = a.shape[0] // P
    me = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % P) for i in range(P)]

    if mode == "sequential":
        full = _dot(a, b)           # compute completes before any hop

        def tile(t):
            start = t * m_local
            return jax.lax.dynamic_slice(
                full, (start, _jnp().zeros((), start.dtype)),
                (m_local, full.shape[1]))

        acc = tile((me + 1) % P)
        for r in range(1, P):
            acc = jax.lax.ppermute(acc, axis, perm) + tile((me + 1 + r) % P)
        return acc.astype(dt)

    def tile_dot(t):
        start = t * m_local
        sl = jax.lax.dynamic_slice(
            a, (start, _jnp().zeros((), start.dtype)),
            (m_local, a.shape[1]))
        return _dot(sl, b)

    acc = tile_dot((me + 1) % P)
    for r in range(1, P):
        # hop the running sum while the next tile's dot computes
        acc_in = jax.lax.ppermute(acc, axis, perm)
        acc = acc_in + tile_dot((me + 1 + r) % P)
    return acc.astype(dt)


# ---------------------------------------------------------------------------
# Global-array wrapper (pads ragged tiles, caches compiled fns)
# ---------------------------------------------------------------------------

def _pad_to(x, dim, multiple):
    jnp = _jnp()
    size = x.shape[dim]
    rem = size % multiple
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[dim] = (0, multiple - rem)
    return jnp.pad(x, pad), size


def _compiled(plan, axis, direction, mode, a, b):
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    key = (plan.cache_token(), axis, direction, mode,
           a.shape, str(a.dtype), b.shape, str(b.dtype))
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn
    size = plan.axis_size(axis)
    if direction == "ag":
        local = lambda al, bl: all_gather_matmul_local(  # noqa: E731
            al, bl, axis=axis, axis_size=size, mode=mode)
        in_specs = (P(axis, None), P(None, None))
        out_specs = P(None, None)
    else:
        local = lambda al, bl: matmul_reduce_scatter_local(  # noqa: E731
            al, bl, axis=axis, axis_size=size, mode=mode)
        in_specs = (P(None, axis), P(axis, None))
        out_specs = P(axis, None)
    mapped = shard_map(local, mesh=plan.mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
    with obs.span(f"compile:sharded_matmul[{direction}/{mode}]",
                  cat="compile", mesh=plan.describe(), axis=axis):
        fn = jax.jit(mapped).lower(a, b).compile()
    _jit_cache[key] = fn
    return fn


def sharded_matmul(a, b, *, direction, plan=None, axis="tp", mode=None):
    """Global-array entry: ``a @ b`` through the overlapped (or
    sequential) ring schedule on ``plan``'s mesh.

    ``direction='ag'``: ``a`` [m, k] row-sharded over ``axis``, ``b``
    replicated.  ``direction='rs'``: contraction dim sharded across
    both operands, output rows reduce-scattered (the global result is
    still the full product).  Ragged dims are zero-padded to the tile
    count and sliced back — uneven last tiles work in both modes.
    """
    from . import sharding as spmd
    jnp = _jnp()
    if plan is None:
        plan = spmd.get_mesh_plan()
    if plan is None or plan.is_virtual or plan.axis_size(axis) <= 1:
        return _dot(a, b).astype(_out_dtype(a, b))
    if mode is None:
        mode = select_mode(plan, axis)
    if direction not in ("ag", "rs"):
        raise ValueError(f"direction must be 'ag' or 'rs', got "
                         f"{direction!r}")
    P = plan.axis_size(axis)
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    m = a.shape[0]
    a, _ = _pad_to(a, 0, P)
    if direction == "rs":
        a, _ = _pad_to(a, 1, P)
        b, _ = _pad_to(b, 0, P)
    fn = _compiled(plan, axis, direction, mode, a, b)
    with obs.span(f"dispatch:sharded_matmul[{direction}]",
                  cat="dispatch", mesh=plan.describe(), axis=axis,
                  mode=mode):
        out = fn(a, b)
    return out[:m] if out.shape[0] != m else out


# ---------------------------------------------------------------------------
# Measured host-driven ring (timeline evidence for the overlap ratio)
# ---------------------------------------------------------------------------

def _measured_fns(plan, axis, a, b):
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    key = ("measured", plan.cache_token(), axis,
           a.shape, str(a.dtype), b.shape, str(b.dtype))
    fns = _jit_cache.get(key)
    if fns is not None:
        return fns
    size = plan.axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]
    rot = shard_map(lambda x: jax.lax.ppermute(x, axis, perm),
                    mesh=plan.mesh, in_specs=P(axis, None),
                    out_specs=P(axis, None), check_rep=False)
    dot = shard_map(
        lambda al, bl: _dot(al, bl).astype(_out_dtype(al, bl)),
        mesh=plan.mesh, in_specs=(P(axis, None), P(None, None)),
        out_specs=P(axis, None), check_rep=False)
    fns = (jax.jit(rot).lower(a).compile(),
           jax.jit(dot).lower(a, b).compile())
    _jit_cache[key] = fns
    return fns


def measured_sharded_matmul(a, b, *, plan=None, axis="tp", mode=None):
    """Drive the all-gather-matmul ring step-wise from the host so the
    timeline records *real* collective/compute spans.

    Each ring hop runs as its own async device call inside a
    ``cat="collective"`` span carrying the axis attr (the same shape
    the eager collectives emit).  Overlapped mode dispatches the
    partial dot while that hop is in flight — the dispatch span nests
    inside the collective span, which is exactly what
    ``phase_breakdown()``'s per-axis overlap ratio measures.
    Sequential mode blocks on the hop first, so its ratio is ~0.

    Returns the full ``a @ b`` product (row-padded dims sliced back).
    """
    import jax
    from . import sharding as spmd
    jnp = _jnp()
    if plan is None:
        plan = spmd.get_mesh_plan()
    if plan is None or plan.is_virtual or plan.axis_size(axis) <= 1:
        raise ValueError("measured_sharded_matmul needs a real plan "
                         f"with axis {axis!r} > 1")
    if mode is None:
        mode = select_mode(plan, axis)
    P = plan.axis_size(axis)
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    m = a.shape[0]
    a, _ = _pad_to(a, 0, P)
    rot, dot = _measured_fns(plan, axis, a, b)
    nb = int(a.size) * a.dtype.itemsize
    out = None
    a_cur = a
    for r in range(P):
        if mode == "overlap" and r < P - 1:
            with obs.span("collective:overlap.ppermute", cat="collective",
                          axis=axis, bytes=nb, mode=mode):
                a_nxt = rot(a_cur)
                with obs.span("dispatch:overlap.partial_dot",
                              cat="dispatch", axis=axis, mode=mode):
                    part = dot(a_cur, b)
                    jax.block_until_ready(part)
                jax.block_until_ready(a_nxt)
        elif mode == "overlap":
            a_nxt = None
            with obs.span("dispatch:overlap.partial_dot", cat="dispatch",
                          axis=axis, mode=mode):
                part = dot(a_cur, b)
                jax.block_until_ready(part)
        else:
            a_nxt = None
            if r < P - 1:
                with obs.span("collective:overlap.ppermute",
                              cat="collective", axis=axis, bytes=nb,
                              mode=mode):
                    a_nxt = rot(a_cur)
                    jax.block_until_ready(a_nxt)
            with obs.span("dispatch:overlap.partial_dot", cat="dispatch",
                          axis=axis, mode=mode):
                part = dot(a_cur, b)
                jax.block_until_ready(part)
        if r == 0:
            # step 0's gathered partials already tile the full product
            # (device j holds shard j); later steps replicate it.
            out = part
        if a_nxt is not None:
            a_cur = a_nxt
    return out[:m] if out.shape[0] != m else out


# ---------------------------------------------------------------------------
# Executor hook: route eligible row-parallel linears through the ring
# ---------------------------------------------------------------------------

def executor_linear_override(plan, mode, routed=None):
    """``op_override`` for ``static.executor.run_program_ops``.

    Intercepts ``linear`` / ``linear_act`` ops whose weight is purely
    row-parallel (legalized spec ``P('tp', ...)`` with nothing on the
    output dim) and replaces the GSPMD all-reduce with a nested
    ``shard_map`` island: ``matmul_reduce_scatter_local`` (the
    overlapped half) + a tiled ``all_gather`` — a decomposed
    all-reduce whose reduce half hides under the partial dots.
    Ineligible ops return ``NotImplemented`` and fall through to the
    plain impl (GSPMD inserts its collective as before).

    ``routed`` (a list, optional) collects the spmd names of routed
    weights at trace time — surfaced in the executor cache entry.
    """
    if plan is None or plan.is_virtual or mode != "overlap" \
            or plan.axis_size("tp") <= 1:
        return None
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from . import sharding as spmd
    from ...nn.functional.common import _apply_act

    tp = plan.axis_size("tp")
    data_axes = plan.data_axes()

    def override(op, vals):
        if op.type not in ("linear", "linear_act"):
            return NotImplemented
        w_t = op.inputs[1]
        if isinstance(w_t, spmd_variable_types()):
            return NotImplemented          # weight is a graph temp
        x, w = vals[0], vals[1]
        bias = vals[2] if len(vals) > 2 else None
        act = op.attrs.get("act") if op.type == "linear_act" else None
        if w.ndim != 2 or x.ndim < 2:
            return NotImplemented
        spec = plan.spec_for(spmd.spmd_name(w_t), w.shape)
        entries = tuple(spec)
        if not entries or entries[0] != "tp":
            return NotImplemented          # not row-parallel
        if any(e is not None for e in entries[1:]):
            return NotImplemented          # fsdp/tp also on out dim
        k = w.shape[0]
        batch0 = x.shape[0]
        dfac = math.prod(plan.axis_sizes[a] for a in data_axes) \
            if data_axes else 1
        if dfac > 1 and batch0 % dfac != 0:
            dfac = 1                       # batch replicated (batch_spec)
        rows_local = (batch0 // dfac) * math.prod(x.shape[1:-1])
        if k % tp != 0 or rows_local % tp != 0:
            return NotImplemented          # ragged tiles: leave to GSPMD
        if x.shape[-1] != k:
            return NotImplemented

        x_batch = data_axes if len(data_axes) > 1 else (
            data_axes[0] if data_axes else None)
        x_spec = P(*((x_batch if dfac > 1 else None,)
                     + (None,) * (x.ndim - 2) + ("tp",)))
        out_spec = P(*((x_batch if dfac > 1 else None,)
                       + (None,) * (x.ndim - 1)))

        def island(xl, wl):
            x2 = xl.reshape((-1, xl.shape[-1]))
            part = matmul_reduce_scatter_local(
                x2, wl, axis="tp", axis_size=tp, mode="overlap")
            full = jax.lax.all_gather(part, "tp", axis=0, tiled=True)
            return full.reshape(xl.shape[:-1] + (wl.shape[-1],))

        mapped = shard_map(island, mesh=plan.mesh,
                           in_specs=(x_spec, P("tp", None)),
                           out_specs=out_spec, check_rep=False)
        z = mapped(x, w)
        if bias is not None:
            z = z + bias
        if act is not None:
            z = _apply_act(z, act)
        if routed is not None:
            routed.append(spmd.spmd_name(w_t))
        return z

    return override


def spmd_variable_types():
    """The framework Variable type(s) — weights must be captured
    tensors, not graph temporaries, for rule lookup to mean anything."""
    from ...static.framework import Variable
    return (Variable,)
