"""Microbatch 1F1B pipeline parallelism over the ``pp`` mesh axis.

The ``pp`` axis (``PADDLE_TPU_MESH="dp=2;pp=2"``) partitions the
*program*, not tensors: each pipeline stage is a pure callable
``stage_fn(params, x) -> y`` compiled under the sub-plan
``MeshPlan.stage_plan(s)`` (the plan minus ``pp``, over that stage's
device slice), so a stage shards exactly like a non-pipelined program
on its subset of the mesh.

Scheduling is classic 1F1B: every stage fills a warmup window of
``min(M, S - s)`` forward microbatches, then strictly alternates
backward/forward until the drain — bounding live activations per stage
to the window instead of GPipe's full ``M``.  The schedule is produced
by :func:`one_f_one_b_order` (a deterministic cycle simulation, unit
testable) and *executed* through ``core.pipeline.InFlightWindow``
instances — one per stage, depth = warmup window + 1 — so the in-flight
accounting, ``pipeline.wait`` spans, and ``pipeline_stats()`` lanes the
async executor already has cover pipeline-parallel runs too.

Numerics: with equal microbatches and a mean-reducing ``loss_fn``, the
pipeline loss is the mean of microbatch losses and gradients are the
mean of microbatch gradients — identical to the full-batch step up to
float summation order (the pp=2 vs pp=1 parity test in
tests/test_sharding.py holds this to rtol 1e-6 in f32).

Memory: :meth:`PipelineSchedule.preflight` routes through
``memory.guard.preflight_check`` with per-stage residents and the
microbatch in-flight activation buffers as a named line item, so the
pipeline's steady state is budgeted before the first dispatch.
"""
from __future__ import annotations

import os

import numpy as np

from ... import observability as obs

__all__ = ["ENV_MICROBATCHES", "PipelineSchedule", "max_in_flight",
           "num_microbatches_default", "one_f_one_b_order"]

ENV_MICROBATCHES = "PADDLE_TPU_MICROBATCHES"


def num_microbatches_default(num_stages):
    """``PADDLE_TPU_MICROBATCHES`` or 2×stages (keeps the pipe full
    through the steady state with a modest activation window)."""
    env = os.environ.get(ENV_MICROBATCHES, "").strip()
    if env:
        n = int(env)
        if n < 1:
            raise ValueError(f"{ENV_MICROBATCHES} must be >= 1, got {n}")
        return n
    return max(1, 2 * int(num_stages))


def one_f_one_b_order(num_stages, num_microbatches):
    """Flat dispatch order ``[(kind, stage, microbatch)]``, kind in
    ``{"F", "B"}``, following the 1F1B schedule.

    Deterministic cycle simulation: per cycle each stage issues at most
    one op, readiness is judged against the previous cycle's state
    (stage ``s`` can forward microbatch ``m`` only after stage ``s-1``
    finished it in an earlier cycle), and once a stage's warmup window
    ``min(M, S - s)`` is full it only drains backwards — stalling if
    none is ready — so per-stage in-flight activations never exceed
    the window (``max_in_flight`` equals it exactly in steady state).
    """
    S, M = int(num_stages), int(num_microbatches)
    if S < 1 or M < 1:
        raise ValueError(f"need >=1 stage and >=1 microbatch, got "
                         f"S={S}, M={M}")
    order = []
    fwd = [0] * S   # forwards issued per stage
    bwd = [0] * S   # backwards issued per stage
    while any(b < M for b in bwd):
        f0, b0 = list(fwd), list(bwd)
        issued = False
        for s in range(S):
            warm = min(M, S - s)
            can_f = fwd[s] < M and (s == 0 or fwd[s] < f0[s - 1])
            can_b = bwd[s] < f0[s] and (s == S - 1 or bwd[s] < b0[s + 1])
            if (f0[s] - b0[s]) >= warm:
                # window full: strictly one-B-then-one-F — drain a
                # backward or STALL; running another forward here is
                # GPipe's memory curve, not 1F1B's
                if can_b:
                    order.append(("B", s, bwd[s]))
                    bwd[s] += 1
                    issued = True
            elif can_f:
                order.append(("F", s, fwd[s]))
                fwd[s] += 1
                issued = True
            elif can_b:
                order.append(("B", s, bwd[s]))
                bwd[s] += 1
                issued = True
        if not issued:
            raise RuntimeError(
                f"1F1B schedule deadlocked at fwd={fwd} bwd={bwd} "
                f"(S={S}, M={M})")
    return order


def max_in_flight(order, num_stages):
    """Per-stage peak of forwarded-but-not-backpropagated microbatches
    observed in ``order`` — the activation window the memory guard
    charges (≤ ``min(M, S - s)`` by construction)."""
    peak = [0] * int(num_stages)
    live = [0] * int(num_stages)
    for kind, s, _ in order:
        live[s] += 1 if kind == "F" else -1
        peak[s] = max(peak[s], live[s])
    return peak


def _tree_add(a, b):
    import jax
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def _tree_scale(a, k):
    import jax
    return jax.tree_util.tree_map(lambda x: x * k, a)


def _split_batch(x, num_microbatches):
    import jax.numpy as jnp
    n = x.shape[0]
    if n % num_microbatches != 0:
        raise ValueError(
            f"batch dim {n} not divisible by num_microbatches="
            f"{num_microbatches}")
    return jnp.split(x, num_microbatches, axis=0)


class PipelineSchedule:
    """1F1B runner over ``len(stage_fns)`` pipeline stages.

    ``stage_fns``: pure callables ``fn(params, x) -> y``.
    ``stage_params``: one parameter pytree per stage.
    ``loss_fn(pred, target) -> scalar`` (mean-reduced) closes the last
    stage.  ``plan`` supplies stage placement (``pp`` axis); ``None``
    or ``pp=1`` runs every stage on the default device — same numbers,
    no pipeline hardware.
    """

    def __init__(self, stage_fns, stage_params, loss_fn, *, plan=None,
                 num_microbatches=None):
        from . import sharding as spmd
        import jax
        from ...core.pipeline import InFlightWindow
        self.stage_fns = list(stage_fns)
        self.num_stages = len(self.stage_fns)
        if self.num_stages < 1:
            raise ValueError("need at least one stage")
        self.loss_fn = loss_fn
        self.plan = plan if plan is not None else spmd.get_mesh_plan()
        if self.plan is not None and self.plan.num_stages > 1 \
                and self.plan.num_stages != self.num_stages:
            raise ValueError(
                f"plan has pp={self.plan.num_stages} but "
                f"{self.num_stages} stage functions were given")
        self.num_microbatches = int(
            num_microbatches if num_microbatches is not None
            else num_microbatches_default(self.num_stages))
        self.order = one_f_one_b_order(self.num_stages,
                                       self.num_microbatches)
        self._peaks = max_in_flight(self.order, self.num_stages)

        piped = (self.plan is not None and not self.plan.is_virtual
                 and self.plan.num_stages > 1)
        self._stage_plans = []
        self._stage_devs = []
        for s in range(self.num_stages):
            sp = self.plan.stage_plan(s) if piped else (
                self.plan if self.plan is not None
                and not self.plan.is_virtual
                and self.plan.num_stages == 1 else None)
            self._stage_plans.append(sp)
            if piped:
                self._stage_devs.append(self.plan.stage_devices(s)[0])
            else:
                self._stage_devs.append(None)
        # place each stage's params on its slice of the mesh
        self.stage_params = []
        for s, params in enumerate(stage_params):
            self.stage_params.append(self._place(s, params))
        # one in-flight window per stage, depth = warmup window + 1,
        # layered on the executor's async-pipeline machinery
        self._windows = [InFlightWindow(depth=self._peaks[s] + 1)
                         for s in range(self.num_stages)]

    def _place(self, stage, tree):
        import jax
        sp = self._stage_plans[stage]
        if sp is not None:
            return jax.device_put(tree, sp.replicated())
        dev = self._stage_devs[stage]
        if dev is not None:
            return jax.device_put(tree, dev)
        return tree

    def _stage_call(self, stage, params, x):
        return self.stage_fns[stage](params, x)

    # -- memory preflight -------------------------------------------------
    def activation_shapes(self, x_microbatch):
        """Per-stage output ShapeDtypeStructs for one microbatch."""
        import jax
        shapes = []
        cur = x_microbatch
        for s in range(self.num_stages):
            cur = jax.eval_shape(self.stage_fns[s],
                                 self.stage_params[s], cur)
            shapes.append(cur)
        return shapes

    def microbatch_buffer_bytes(self, x_microbatch):
        """Bytes of the 1F1B in-flight activation window: each stage
        holds up to its warmup peak of forwarded microbatch outputs."""
        import jax
        total = 0
        for s, sds in enumerate(self.activation_shapes(x_microbatch)):
            act = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(sds))
            total += self._peaks[s] * act
        return int(total)

    def preflight(self, x, y=None, budget=None, raise_on_over=True):
        """Budget the pipeline's steady state before dispatching.

        Named line items: per-stage parameter residents plus the
        microbatch in-flight activation buffers (the 1F1B window).
        The compiled estimate comes from stage 0's AOT lowering; the
        line items carry the cross-stage state it cannot see.
        """
        import jax
        from ...memory import guard
        from ...memory.estimator import named_buffer_sizes
        x_mb = _split_batch(jax.numpy.asarray(x),
                            self.num_microbatches)[0]
        named = []
        for s, params in enumerate(self.stage_params):
            leaves = jax.tree_util.tree_leaves(params)
            rows = named_buffer_sizes(
                [(f"pp stage {s} residents", l) for l in leaves])
            named.append((f"pp stage {s} residents",
                          sum(n for _, n in rows)))
        named.append(("pp microbatch in-flight buffers",
                      self.microbatch_buffer_bytes(x_mb)))
        try:
            compiled = jax.jit(self.stage_fns[0]).lower(
                self.stage_params[0], x_mb).compile()
        except Exception:
            compiled = None
        return guard.preflight_check(
            compiled, program=f"pipeline_1f1b[S={self.num_stages},"
            f"M={self.num_microbatches}]", named_buffers=named,
            budget=budget, raise_on_over=raise_on_over)

    # -- the 1F1B step ----------------------------------------------------
    def step(self, x, y):
        """One pipelined training step: ``(loss, [stage_grads])``.

        ``loss`` is the mean of microbatch losses; gradients are the
        mean of microbatch gradients — full-batch parity for
        mean-reducing losses.
        """
        import jax
        xs = _split_batch(x, self.num_microbatches)
        ys = _split_batch(y, self.num_microbatches)
        S, M = self.num_stages, self.num_microbatches
        outs, vjps, cots = {}, {}, {}
        losses = [None] * M
        grads = [None] * S
        loss_grad = jax.value_and_grad(self.loss_fn)
        for kind, s, m in self.order:
            if kind == "F":
                xin = xs[m] if s == 0 else outs[(s - 1, m)]
                xin = self._place(s, xin)      # stage-to-stage transfer
                with obs.span(f"dispatch:pp.fwd[s{s}]", cat="dispatch",
                              step=m, stage=s):
                    out, vjp = jax.vjp(
                        lambda p, t, _s=s: self._stage_call(_s, p, t),
                        self.stage_params[s], xin)
                outs[(s, m)] = out
                vjps[(s, m)] = vjp
                self._windows[s].admit(
                    jax.tree_util.tree_leaves(out),
                    label=f"pp.fwd:s{s}", step=m)
            else:
                if s == S - 1:
                    loss, dy = loss_grad(outs[(s, m)],
                                         self._place(s, ys[m]))
                    losses[m] = loss
                else:
                    dy = cots.pop((s, m))
                dy = self._place(s, dy)
                with obs.span(f"dispatch:pp.bwd[s{s}]", cat="dispatch",
                              step=m, stage=s):
                    dparams, dx = vjps.pop((s, m))(dy)
                grads[s] = dparams if grads[s] is None \
                    else _tree_add(grads[s], dparams)
                if s > 0:
                    cots[(s - 1, m)] = dx
                outs.pop((s, m), None)
        for w in self._windows:
            w.drain()
        import jax.numpy as jnp
        loss = jnp.mean(jnp.stack(losses))
        grads = [_tree_scale(g, 1.0 / M) for g in grads]
        return loss, grads
