"""Measured alpha-beta calibration for the auto-parallel cost model.

VERDICT r4 weak #7 / next #10: the planner's alpha-beta model was
"effectively uncalibrated" — ordering invariants had been checked
against a single measured psum point.  This module closes the loop:

  * :func:`measure_collectives` times real ``psum`` / ``all_gather`` /
    ``ppermute`` collectives (via ``shard_map`` over the current mesh)
    across a size sweep, per mesh axis size;
  * :func:`fit_alpha_beta` least-squares fits ``t = alpha * steps +
    wire_bytes / beta`` per collective kind — the same functional form
    :func:`..cost_model.comm_cost_seconds` evaluates;
  * :func:`save_fit` / :func:`load_fit` persist the fit
    (``.bench_cache/comm_fit.json`` by default, override with
    ``PADDLE_TPU_COMM_FIT``), and :func:`install_fit` makes
    ``comm_cost_seconds`` — and therefore every ``Planner`` decision —
    consume the measured constants instead of the v5e datasheet
    defaults.

Reference parity: the reference's auto-parallel cost model ships
cluster profiles measured by its own collective benchmark
(`auto_parallel/static/cost/comm_op_cost.py` + cluster topology json)
[UNVERIFIED — empty reference mount; SURVEY.md §2.3 auto-parallel row].
The TPU-native redesign measures XLA collectives on the actual mesh
(CPU ring in tests, ICI when run on hardware) rather than tabulating
NCCL primitives.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = [
    "measure_collectives", "fit_alpha_beta", "save_fit", "load_fit",
    "install_fit", "default_fit_path", "calibrate",
]


def default_fit_path():
    p = os.environ.get("PADDLE_TPU_COMM_FIT")
    if p:
        return p
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    return os.path.join(root, ".bench_cache", "comm_fit.json")


def _collective_fn(kind, axis):
    import jax
    import jax.numpy as jnp

    if kind == "all_reduce":
        def f(x):
            return jax.lax.psum(x, axis)
    elif kind == "all_gather":
        def f(x):
            return jax.lax.all_gather(x, axis)
    elif kind == "reduce_scatter":
        def f(x):
            return jax.lax.psum_scatter(x, axis, tiled=True)
    elif kind == "permute":
        def f(x):
            from ..jax_compat import axis_size as _axis_size
            n = _axis_size(axis)
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, axis, perm)
    else:
        raise ValueError(f"unknown collective kind {kind!r}")
    return f


def measure_collectives(mesh, axis, sizes=None, kinds=None, reps=5):
    """Time collectives over ``mesh``'s ``axis`` at each payload size.

    ``sizes`` are PER-SHARD payload bytes (f32).  Returns
    ``{kind: [(nbytes, seconds), ...]}`` with ``nbytes`` converted to
    the GLOBAL-array convention ``comm_cost_seconds`` uses (gathered
    size for all_gather), median wall seconds of ``reps`` synced calls.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sizes = sizes or [1 << 12, 1 << 16, 1 << 20, 1 << 22]
    kinds = kinds or ["all_reduce", "all_gather", "reduce_scatter",
                      "permute"]
    n = int(mesh.shape[axis])
    out = {k: [] for k in kinds}
    for kind in kinds:
        f = _collective_fn(kind, axis)
        for nbytes in sizes:
            elems = max(n, nbytes // 4)
            # global array: one shard of `elems` per mesh slice
            xs = jnp.zeros((n * elems,), jnp.float32) + 1.0
            sharded = jax.device_put(
                xs, NamedSharding(mesh, P(axis)))
            from ..jax_compat import shard_map as _shard_map
            g = jax.jit(_shard_map(
                f, mesh=mesh, in_specs=P(axis),
                out_specs=P(axis) if kind in ("reduce_scatter",
                                              "permute", "all_reduce")
                else P()))
            jax.block_until_ready(g(sharded))  # compile
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(g(sharded))
                ts.append(time.perf_counter() - t0)
            # record in comm_cost_seconds' GLOBAL-array convention: the
            # per-shard payload here is `elems` f32; all_gather's
            # logical array is the GATHERED one (n x larger)
            shard_bytes = float(elems * 4)
            logical = shard_bytes * n if kind == "all_gather" \
                else shard_bytes
            out[kind].append((logical, float(np.median(ts))))
    return out


def fit_alpha_beta(samples, axis_size):
    """Least-squares ``t = alpha * steps + wire / beta`` per kind.

    ``samples``: {kind: [(nbytes, seconds)]}.  Returns
    {kind: {"alpha": s/step, "beta": bytes/s}} with both clamped
    positive (a negative LSQ intercept collapses to the smallest
    observed latency share).
    """
    from .cost_model import ring_steps_wire
    fits = {}
    for kind, pts in samples.items():
        if len(pts) < 2:
            continue
        rows, ts = [], []
        for nbytes, sec in pts:
            steps, wire = ring_steps_wire(kind, nbytes, axis_size)
            rows.append([float(steps), wire])
            ts.append(sec)
        A = np.asarray(rows)
        t = np.asarray(ts)
        (a, inv_b), *_ = np.linalg.lstsq(A, t, rcond=None)
        if a <= 0:
            # latency hid under the wire term: charge the smallest
            # observed time fully to alpha
            a = max(min(t) / max(A[:, 0].max(), 1.0), 1e-9)
        if inv_b <= 0:
            inv_b = 1e-12  # effectively free wire: bandwidth-unbound
        fits[kind] = {"alpha": float(a), "beta": float(1.0 / inv_b)}
    return fits


def save_fit(fits, axis_size, platform, path=None):
    path = path or default_fit_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "axis_size": int(axis_size),
        "platform": str(platform),
        "captured_unix": int(time.time()),
        "fits": fits,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return path


def load_fit(path=None):
    path = path or default_fit_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def install_fit(fits):
    """Make ``comm_cost_seconds`` (and every Planner) use ``fits``."""
    from . import cost_model
    cost_model._MEASURED_FIT = dict(fits)


def calibrate(mesh, axis, install=True, save=True, **kw):
    """Measure → fit → (install, persist).  Returns the fit dict."""
    import jax
    samples = measure_collectives(mesh, axis, **kw)
    fits = fit_alpha_beta(samples, int(mesh.shape[axis]))
    if install:
        install_fit(fits)
    if save:
        save_fit(fits, int(mesh.shape[axis]),
                 jax.devices()[0].platform)
    return fits
