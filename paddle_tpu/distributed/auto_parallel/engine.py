"""Semi-auto parallel engine: dist.to_static / DistModel / Engine.

Role of the reference's `auto_parallel/engine.py` +
`auto_parallel/api.py::to_static` (semi-auto static training: user
marks a few tensors, completion/partitioner/reshard passes produce the
per-rank program [UNVERIFIED — empty reference mount]).

TPU-native: the "partitioned program" is ONE SPMD XLA executable.
`DistModel` captures the layer's train/eval/predict step as a pure
function of (params, opt_state, *data), places parameters according to
(a) placements the user already attached via `shard_tensor`/
`shard_layer`, then (b) the cost-model `Planner` for the rest, and jits
the step with donated state.  XLA's sharding propagation completes the
placement of every intermediate (see completion.py) and inserts the
collectives the reference's reshard pass would have inserted.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Strategy", "DistModel", "to_static", "Engine"]


class _Namespace:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class Strategy:
    """Mirrors paddle.distributed.Strategy: nested feature configs."""

    def __init__(self, config=None):
        self.sharding = _Namespace(enable=False, degree=1, stage=1)
        self.amp = _Namespace(enable=False, dtype="float16", level="O1")
        self.recompute = _Namespace(enable=False)
        self.pipeline = _Namespace(enable=False, schedule_mode="1F1B",
                                   accumulate_steps=1)
        self.gradient_merge = _Namespace(enable=False, k_steps=1)
        if config:
            for k, v in config.items():
                ns = getattr(self, k, None)
                if ns is None:
                    setattr(self, k, _Namespace(**v))
                else:
                    ns.__dict__.update(v)


def _global_mesh():
    from .api import get_mesh
    from ..env import global_mesh
    from .sharding import get_mesh_plan
    m = get_mesh()
    if m is not None:
        return m.jax_mesh() if hasattr(m, "jax_mesh") else m
    plan = get_mesh_plan()
    if plan is not None and not plan.is_virtual:
        return plan.mesh
    return global_mesh()


class DistModel:
    """A Layer compiled into sharded SPMD train/eval/predict steps."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None, mesh=None):
        import jax
        self._layer = layer
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._metrics = metrics or []
        # seed-era Strategy flags must not silently run single-device:
        # pipeline and gradient_merge have no SPMD lowering here —
        # refuse loudly and name the supported path.  sharding.enable
        # delegates to a MeshPlan (fsdp axis of the requested degree).
        for feature in ("pipeline", "gradient_merge"):
            if getattr(getattr(self._strategy, feature), "enable", False):
                raise NotImplementedError(
                    f"Strategy.{feature}.enable is not lowered by this "
                    "engine and would silently run single-device. Use "
                    "paddle_tpu.distributed.auto_parallel.sharding."
                    "MeshPlan (env PADDLE_TPU_MESH, e.g. 'dp=4,tp=2') "
                    "with static.Executor or jit.to_static instead.")
        if mesh is None and getattr(self._strategy.sharding, "enable",
                                    False):
            degree = int(getattr(self._strategy.sharding, "degree", 1)
                         or 1)
            if degree > 1:
                from .sharding import MeshPlan
                mesh = MeshPlan(f"fsdp={degree}").mesh
        self._mesh = mesh or _global_mesh()
        self._mode = "train" if optimizer is not None else "predict"
        self._steps = {}

        self._params = list(layer.parameters())
        self._trainable = [p for p in self._params if not p.stop_gradient]
        named = {}
        for name, p in getattr(layer, "named_parameters", lambda: [])():
            named[id(p)] = name
        self._param_names = [named.get(id(p), f"p{i}")
                             for i, p in enumerate(self._trainable)]
        self._place_state()
        if optimizer is not None:
            self._opt_state = optimizer._ensure_static_state(
                self._trainable)
            self._place_opt_state()
        else:
            self._opt_state = []

    # -- placement ------------------------------------------------------
    def _plan_entries(self, p, name):
        user = getattr(p, "placements", None)
        if user is not None:
            from .api import Shard
            # resolve axis names against the mesh the tensor was placed
            # on when it differs from the engine mesh (shard_tensor
            # stores it as .process_mesh)
            pmesh = getattr(p, "process_mesh", None)
            names = (pmesh.dim_names if pmesh is not None
                     else self._mesh.axis_names)
            entries = [None] * p.ndim
            for axis_i, pl in enumerate(user):
                if isinstance(pl, Shard) and axis_i < len(names) and \
                        names[axis_i] in self._mesh.axis_names:
                    entries[pl.dim] = names[axis_i]
            return entries
        return self._auto_plan.get(name, [None] * p.ndim)

    def _place_state(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .cost_model import Planner
        planner = Planner(self._mesh)
        shapes = {n: tuple(p.shape)
                  for n, p in zip(self._param_names, self._trainable)}
        self._auto_plan = planner.plan(shapes)
        self._shard_by_shape = {}
        for n, p in zip(self._param_names, self._trainable):
            entries = self._plan_entries(p, n)
            sh = NamedSharding(self._mesh, P(*entries))
            try:
                p._value = jax.device_put(p._value, sh)
            except ValueError:
                sh = NamedSharding(self._mesh, P())
                p._value = jax.device_put(p._value, sh)
            self._shard_by_shape.setdefault(tuple(p.shape), sh)

    def _place_opt_state(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(self._mesh, P())
        for t in self._opt_state:
            sh = self._shard_by_shape.get(tuple(t.shape), rep)
            try:
                t._value = jax.device_put(t._value, sh)
            except ValueError:
                t._value = jax.device_put(t._value, rep)

    def _data_sharding(self, ndim):
        from jax.sharding import NamedSharding, PartitionSpec as P
        axes = [a for a in ("dp", "data", "sharding", "fsdp")
                if a in self._mesh.axis_names and self._mesh.shape[a] > 1]
        if not axes or ndim == 0:
            return NamedSharding(self._mesh, P())
        return NamedSharding(self._mesh,
                             P(tuple(axes), *([None] * (ndim - 1))))

    # -- step builders ---------------------------------------------------
    def _bind_forward(self, pvals, args):
        import contextlib
        from ...core.tensor import Tensor
        from ...core.autograd import no_grad
        saved = [(p, p._value) for p in self._trainable]
        try:
            for p, v in zip(self._trainable, pvals):
                p._value = v
            ins = [Tensor(a, _internal=True, stop_gradient=True)
                   if not isinstance(a, Tensor) else a for a in args]
            ctx = contextlib.nullcontext()
            amp = self._strategy.amp
            if getattr(amp, "enable", False):
                from ... import amp as amp_mod
                ctx = amp_mod.auto_cast(dtype=amp.dtype, level=amp.level)
            with ctx:
                if self._mode == "train":
                    out = self._layer(*ins)
                else:
                    with no_grad():
                        out = self._layer(*ins)
            return out
        finally:
            for p, v in saved:
                p._value = v

    def _build_step(self, mode, data_avals):
        import jax
        import jax.numpy as jnp
        from ...core.tensor import Tensor

        def tval(x):
            return x._value if isinstance(x, Tensor) else x

        if mode == "predict":
            def step(pvals, opt_vals, lr, step_i, *data):
                del lr, step_i
                out = self._bind_forward(pvals, data)
                if isinstance(out, (tuple, list)):
                    return tuple(tval(o) for o in out), pvals, opt_vals
                return tval(out), pvals, opt_vals
            donate = ()
        else:
            n_label = 1

            def loss_of(pvals, data):
                feats, labels = data[:-n_label], data[-n_label:]
                out = self._bind_forward(pvals, feats)
                lbl = [Tensor(l, _internal=True, stop_gradient=True)
                       for l in labels]
                loss = self._loss(out, *lbl) if self._loss is not None \
                    else out
                return tval(loss).astype(jnp.float32)

            if mode == "eval":
                def step(pvals, opt_vals, lr, step_i, *data):
                    del lr, step_i
                    return loss_of(pvals, data), pvals, opt_vals
                donate = ()
            else:
                def step(pvals, opt_vals, lr, step_i, *data):
                    loss, grads = jax.value_and_grad(loss_of)(
                        tuple(pvals), data)
                    new_p, new_o = self._optimizer._static_update(
                        pvals, grads, opt_vals, self._trainable, lr=lr,
                        step=step_i)
                    return loss, tuple(new_p), tuple(new_o)
                donate = (0, 1)

        from ...framework.flags import get_flags
        if not get_flags("FLAGS_buffer_donation")["FLAGS_buffer_donation"]:
            donate = ()
        return jax.jit(step, donate_argnums=donate)

    # -- public API ------------------------------------------------------
    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def predict(self):
        self._mode = "predict"

    def __call__(self, *data):
        import jax
        import jax.numpy as jnp
        from ...core.tensor import Tensor, to_tensor

        arrs = []
        for d in data:
            v = d._value if isinstance(d, Tensor) else jnp.asarray(
                np.asarray(d))
            arrs.append(jax.device_put(v, self._data_sharding(v.ndim)))
        key = (self._mode, tuple((a.shape, str(a.dtype)) for a in arrs))
        fn = self._steps.get(key)
        if fn is None:
            fn = self._build_step(self._mode, arrs)
            self._steps[key] = fn
        from ...core.lazy import concrete_values
        pvals = concrete_values(self._trainable)
        ovals = concrete_values(self._opt_state)
        lr = jnp.asarray(0.0, jnp.float32)
        step_i = jnp.asarray(0, jnp.int32)
        if self._optimizer is not None:
            opt = self._optimizer
            opt._sync_lr()
            lr = jnp.asarray(opt._lr_tensor._value, jnp.float32)
            step_i = jnp.asarray(np.asarray(opt._step_count._value),
                                 jnp.int32)
            if self._mode == "train":
                opt._step_count._inplace_update(
                    np.asarray(opt._step_count._value) + 1)
        out, new_p, new_o = fn(pvals, ovals, lr, step_i, *arrs)
        for p, v in zip(self._trainable, new_p):
            p._value = v
        for t, v in zip(self._opt_state, new_o):
            t._value = v
        if isinstance(out, tuple):
            return tuple(to_tensor(o) for o in out)
        return to_tensor(out)

    def state_dict(self, mode="all"):
        sd = self._layer.state_dict()
        if mode in ("all", "opt") and self._optimizer is not None:
            sd.update(self._optimizer.state_dict())
        return sd

    def dist_main_program(self, mode=None):
        return None  # one SPMD executable; no per-rank program exists

    @property
    def mesh(self):
        return self._mesh


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None):
    """paddle.distributed.to_static: build a DistModel around a Layer
    whose parameters may carry `shard_tensor` placements."""
    return DistModel(layer, loader, loss, optimizer, strategy)


class Engine:
    """auto_parallel Engine: prepare/fit/evaluate/predict/save/load."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._dist_model = None
        self.history = []

    def prepare(self, *args, **kwargs):
        self._ensure()

    def _ensure(self):
        if self._dist_model is None:
            self._dist_model = DistModel(
                self._model, loss=self._loss, optimizer=self._optimizer,
                strategy=self._strategy, metrics=self._metrics)
        return self._dist_model

    def _batches(self, data, batch_size):
        from ...io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            yield from data
            return
        if hasattr(data, "__getitem__") and not isinstance(
                data, (list, tuple)):
            loader = DataLoader(data, batch_size=batch_size or 1,
                                shuffle=False)
            yield from loader
            return
        yield data

    def fit(self, train_data, epochs=1, batch_size=None, verbose=0,
            **kwargs):
        dm = self._ensure()
        dm.train()
        for ep in range(epochs):
            losses = []
            for batch in self._batches(train_data, batch_size):
                loss = dm(*batch)
                losses.append(float(np.asarray(loss.numpy())))
            self.history.append({"epoch": ep, "loss":
                                 float(np.mean(losses)) if losses else None})
        return self.history

    def evaluate(self, valid_data, batch_size=None, **kwargs):
        dm = self._ensure()
        dm.eval()
        losses = [float(np.asarray(dm(*batch).numpy()))
                  for batch in self._batches(valid_data, batch_size)]
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, batch_size=None, **kwargs):
        dm = self._ensure()
        dm.predict()
        outs = []
        for batch in self._batches(test_data, batch_size):
            o = dm(*batch)
            outs.append(o)
        return outs

    def save(self, path, training=True):
        from ... import save as paddle_save
        dm = self._ensure()
        paddle_save(dm.state_dict("all" if training else "model"),
                    path + ".pdparams")

    def load(self, path):
        from ... import load as paddle_load
        sd = paddle_load(path + ".pdparams")
        self._model.set_state_dict(sd)

    @property
    def main_program(self):
        return None
