"""Auto-parallel (semi-auto) API: shard_tensor / ProcessMesh / placements.

Reference parity: `python/paddle/distributed/auto_parallel/api.py` +
`phi/core/distributed/auto_parallel/` DistTensor/TensorDistAttr/reshard
[UNVERIFIED — empty reference mount].

TPU-native: this IS the jax model (SURVEY.md §2.3) — ProcessMesh maps to
jax.sharding.Mesh, Shard(d)/Replicate/Partial map to PartitionSpec entries,
shard_tensor → device_put(NamedSharding), and reshard is just another
device_put (XLA plans the collective movement, playing the role of the
reference's reshard functions s_to_r/r_to_s/p_to_r).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ..env import set_global_mesh

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_op", "reshard", "dtensor_from_fn", "shard_layer",
           "get_mesh", "set_mesh", "to_static"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicated(self):
        return False

    def is_partial(self):
        return False

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return True

    def is_partial(self):
        return False

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    def __init__(self, reduce_type=None):
        self.reduce_type = reduce_type

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return True

    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    """paddle.distributed.ProcessMesh ↔ jax.sharding.Mesh."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._process_ids = arr.reshape(-1).tolist()
        self._dim_names = list(dim_names) if dim_names is not None else \
            [f"d{i}" for i in range(arr.ndim)]
        self._jax_mesh = None

    @property
    def shape(self):
        return self._shape

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def ndim(self):
        return len(self._shape)

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def jax_mesh(self):
        if self._jax_mesh is None:
            devs = np.asarray(jax.devices())
            n_needed = int(np.prod(self._shape))
            if len(devs) < n_needed:
                # tests on fewer devices: tile the device list (placement
                # degrades to best-effort)
                reps = -(-n_needed // len(devs))
                devs = np.tile(devs, reps)[:n_needed]
            else:
                devs = devs[self._process_ids] if max(
                    self._process_ids) < len(devs) else devs[:n_needed]
            self._jax_mesh = Mesh(devs.reshape(self._shape),
                                  tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                self._shape == other._shape and
                self._dim_names == other._dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


_current_mesh = None


def get_mesh():
    return _current_mesh


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    if isinstance(mesh, ProcessMesh):
        try:
            set_global_mesh(mesh.jax_mesh())
        except Exception:
            pass


def _placements_to_spec(placements, ndim):
    entries = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            entries[pl.dim] = _axis_name_of(axis_i)
    return entries


_ACTIVE_MESH_FOR_SPEC = [None]


def _axis_name_of(axis_i):
    mesh = _ACTIVE_MESH_FOR_SPEC[0]
    return mesh._dim_names[axis_i]


def shard_tensor(data, mesh=None, placements=None, dtype=None, place=None,
                 stop_gradient=None, process_mesh=None, dist_attr=None):
    """Place `data` on the mesh with the given placements.

    Returns a Tensor whose jax.Array carries the NamedSharding — every
    subsequent op propagates it (the completion pass of the reference is
    XLA's sharding propagation).
    """
    from ...core.tensor import to_tensor

    mesh = mesh or process_mesh or _current_mesh
    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    if mesh is None or placements is None:
        return t
    jmesh = mesh.jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    _ACTIVE_MESH_FOR_SPEC[0] = mesh if isinstance(mesh, ProcessMesh) else \
        None
    ndim = t.ndim
    entries = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = (mesh.dim_names[axis_i]
                    if isinstance(mesh, ProcessMesh)
                    else jmesh.axis_names[axis_i])
            entries[pl.dim] = name
    sharding = NamedSharding(jmesh, P(*entries))
    try:
        arr = jax.device_put(t._value, sharding)
    except Exception:
        arr = t._value  # fewer devices than mesh (unit tests): keep local
    out = Tensor(arr, _internal=True,
                 stop_gradient=t.stop_gradient if stop_gradient is None
                 else stop_gradient)
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def reshard(x, mesh, placements):
    return shard_tensor(x, mesh, placements)


def shard_op(op, mesh=None, in_placements=None, out_placements=None):
    def wrapper(*args, **kwargs):
        return op(*args, **kwargs)

    return wrapper


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Apply `shard_fn(name, layer, mesh)` to place each sublayer's params."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def to_static(layer, loader=None, loss_fn=None, optimizer=None,
              strategy=None):
    """auto_parallel dist-model compile entry — delegates to
    engine.DistModel (one SPMD executable with planner-placed state)."""
    from .engine import to_static as _to_static
    return _to_static(layer, loader, loss_fn, optimizer, strategy)
