"""Cost model + rule-based planner for semi-auto parallelism.

Role of the reference's `auto_parallel/static/` planner stack
(completion pass, partitioner, `cost_model.py` op/comm cost estimation
[UNVERIFIED — empty reference mount]).  The division of labor is
TPU-native:

  * **completion** (propagating dist attrs op-by-op through the graph)
    is XLA's sharding propagation — `completion.py` exposes it from the
    compiled executable rather than reimplementing it;
  * **partitioning** (rewriting the program per rank) is SPMD under
    `jit` — there is nothing to rewrite;
  * what remains genuinely ours is the **decision**: which mesh axes to
    use for which tensors.  This module estimates compute/memory from
    the jaxpr and communication from an alpha-beta model over ICI, and
    `Planner` uses those estimates to pick parameter placements.

Numbers are order-of-magnitude estimates for ranking alternatives, not
measurements (use paddle.profiler for those).
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np

__all__ = ["CostEstimate", "estimate_cost", "comm_cost_seconds", "Planner"]

# per-chip estimates used for ranking (v5e-class defaults)
_PEAK_FLOPS = 197e12          # bf16 MXU
_HBM_BW = 8.1e11              # bytes/s
_ICI_BW = 4.5e10              # bytes/s per link direction (one axis)
_ICI_LAT = 1e-6               # seconds per hop


@dataclasses.dataclass
class CostEstimate:
    flops: float = 0.0
    bytes_accessed: float = 0.0
    param_bytes: float = 0.0

    @property
    def compute_seconds(self):
        return max(self.flops / _PEAK_FLOPS,
                   self.bytes_accessed / _HBM_BW)

    def __add__(self, other):
        return CostEstimate(self.flops + other.flops,
                            self.bytes_accessed + other.bytes_accessed,
                            self.param_bytes + other.param_bytes)


def _aval_bytes(aval) -> float:
    try:
        return float(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:
        return 0.0


def _dot_flops(eqn) -> float:
    (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    batch = math.prod(a.shape[d] for d in lhs_b) if lhs_b else 1
    contract = math.prod(a.shape[d] for d in lhs_c) if lhs_c else 1
    m = math.prod(a.shape[d] for d in range(a.ndim)
                  if d not in lhs_c and d not in lhs_b)
    n = math.prod(b.shape[d] for d in range(b.ndim)
                  if d not in rhs_c and d not in rhs_b)
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # out elements x (2 * kernel volume * in-channels)
    return 2.0 * float(np.prod(out.shape)) * float(np.prod(rhs.shape[:-1]))


def estimate_cost(fn, *example_args) -> CostEstimate:
    """Walk fn's jaxpr and accumulate FLOPs (dot/conv) + bytes touched.

    `example_args` may be arrays or ShapeDtypeStructs."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*example_args)
    est = CostEstimate()
    seen_calls = [jaxpr.jaxpr]
    while seen_calls:
        jx = seen_calls.pop()
        for eqn in jx.eqns:
            name = eqn.primitive.name
            for sub in eqn.params.values():
                core = getattr(sub, "jaxpr", None)
                if core is not None:
                    seen_calls.append(getattr(core, "jaxpr", core))
            if name == "dot_general":
                est.flops += _dot_flops(eqn)
            elif name == "conv_general_dilated":
                est.flops += _conv_flops(eqn)
            est.bytes_accessed += sum(
                _aval_bytes(v.aval) for v in eqn.outvars)
    for v in jaxpr.jaxpr.invars:
        est.param_bytes += _aval_bytes(v.aval)
    return est


# measured alpha-beta constants installed by calibration.install_fit()
# (auto-loaded once from .bench_cache/comm_fit.json when present and
# recorded on the SAME platform as the running backend);
# None → datasheet defaults above
_MEASURED_FIT = None
_FIT_LOADED = False


def _current_platform():
    """Backend platform WITHOUT forcing jax init (None if undecided)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.devices()[0].platform
    except Exception:
        return None


def _measured(kind):
    global _MEASURED_FIT, _FIT_LOADED
    if _MEASURED_FIT is None and not _FIT_LOADED:
        _FIT_LOADED = True
        try:
            from .calibration import load_fit
            payload = load_fit()
            if payload:
                plat = _current_platform()
                if plat is None or payload.get("platform") == plat:
                    _MEASURED_FIT = payload["fits"]
                else:
                    import logging
                    logging.getLogger("paddle_tpu.auto_parallel").warning(
                        "ignoring persisted comm fit measured on %r "
                        "(running on %r); re-run calibration.calibrate()",
                        payload.get("platform"), plat)
        except Exception:
            pass
    if _MEASURED_FIT is None:
        return None
    # all_to_all rides the all_gather constants (same ring wire volume)
    return _MEASURED_FIT.get(
        kind, _MEASURED_FIT.get("all_gather")
        if kind == "all_to_all" else None)


def ring_steps_wire(kind: str, nbytes: float, axis_size: int):
    """(hop steps, per-link wire bytes) of one collective on a ring.

    THE single definition of the ring model — ``comm_cost_seconds``
    evaluates it and ``calibration.fit_alpha_beta`` builds its design
    matrix from it, so the two can never drift.  ``nbytes`` convention:
    the GLOBAL logical array (gathered size for all_gather /
    all_to_all, the reduced array for all_reduce / reduce_scatter, the
    payload for permute).
    """
    steps = axis_size - 1
    if kind == "all_reduce":
        return 2 * steps, 2.0 * nbytes * steps / axis_size   # rs + ag
    if kind in ("all_gather", "reduce_scatter", "all_to_all"):
        return steps, nbytes * steps / axis_size
    if kind == "permute":
        return 1, float(nbytes)
    raise ValueError(f"unknown collective kind {kind!r}")


def comm_cost_seconds(nbytes: float, axis_size: int, kind: str) -> float:
    """Alpha-beta estimate of one collective on an ICI ring axis.

    kind: all_reduce | all_gather | reduce_scatter | all_to_all | permute

    Constants come from a measured calibration fit when one is
    installed/persisted (see ``calibration.py``), else v5e datasheet
    estimates.
    """
    if axis_size <= 1 or nbytes <= 0:
        return 0.0
    fit = _measured(kind)
    per_hop = fit["alpha"] if fit else _ICI_LAT
    bw = fit["beta"] if fit else _ICI_BW
    steps, wire = ring_steps_wire(kind, nbytes, axis_size)
    return steps * per_hop + wire / bw


class Planner:
    """Pick parameter placements on a mesh from cost estimates.

    Rules (ranked by estimated step cost, see plan()):
      * 'mp'/'tp' axis present → Megatron-shard big 2-D weights: last
        dim for even layers of matmul chains doesn't matter to XLA —
        we shard the LARGER dim so the per-chip shard and its
        collective are both smaller;
      * 'sharding'/'fsdp' axis present → ZeRO-3 style: shard dim 0 of
        every param whose size crosses `fsdp_threshold`;
      * otherwise replicate (pure DP: grads all-reduced by XLA).
    """

    def __init__(self, mesh, fsdp_threshold: int = 1 << 16):
        self.mesh = mesh
        self.fsdp_threshold = fsdp_threshold

    def _axis(self, *names):
        for n in names:
            if n in self.mesh.axis_names and self.mesh.shape[n] > 1:
                return n
        return None

    def plan(self, named_shapes: dict) -> dict:
        """{param_name: shape} → {param_name: PartitionSpec entries list}"""
        tp = self._axis("mp", "tp", "model")
        fsdp = self._axis("sharding", "fsdp")
        out = {}
        for name, shape in named_shapes.items():
            entries = [None] * len(shape)
            placed = False
            if tp is not None and len(shape) >= 2:
                big = int(np.argmax(shape))
                if shape[big] % self.mesh.shape[tp] == 0 and \
                        np.prod(shape) >= self.fsdp_threshold:
                    entries[big] = tp
                    placed = True
            if not placed and fsdp is not None and len(shape) >= 1:
                if np.prod(shape) >= self.fsdp_threshold and \
                        shape[0] % self.mesh.shape[fsdp] == 0:
                    entries[0] = fsdp
            out[name] = entries
        return out

    def estimate_step_seconds(self, cost: CostEstimate,
                              dp_bytes: float = None) -> float:
        """Compute + the DP gradient all-reduce (the dominant collective
        in the replicated plan); used to compare plan alternatives."""
        dp = self._axis("dp", "data")
        t = cost.compute_seconds
        if dp is not None:
            t += comm_cost_seconds(dp_bytes or cost.param_bytes,
                                   self.mesh.shape[dp], "all_reduce")
        return t
