"""Dropless MoE token routing + ring all-to-all expert dispatch.

The capacity formulation in ``incubate/.../moe_layer.py`` drops every
token past slot ``C`` of its expert (``keep = loc < C``).  This module
is the dropless alternative the grouped-expert Pallas kernel
(`ops.pallas_grouped`) is built for: every (token, expert) assignment
gets a real row in a block-aligned grouped buffer, experts own whole
``block_rows``-row runs described by `pallas_tiles.group_segments`, and
nothing is ever dropped — load imbalance costs padding, not quality.

Routing is three pure pieces (all jnp-traceable, fully deterministic —
the stable argsort gives tokens of one expert their arrival order):

  * `dropless_plan`   — top-k assignments -> (row of each assignment,
    block_group descriptor for the kernel, per-expert counts);
  * `dropless_dispatch` — scatter tokens into the grouped buffer;
  * `dropless_combine`  — gather expert outputs back and weighted-sum
    the k choices per token.

Expert parallelism crosses the ``ep`` mesh axis with all-to-all.
`ring_all_to_all_local` decomposes that collective into per-peer
``ppermute`` hops — the PR 11 ring-overlap discipline
(`overlap.all_gather_matmul_local`): in overlapped mode every hop is
independent of the expert matmul that follows, so XLA schedules the
transfer under the MXU; the sequential fallback is one
``jax.lax.all_to_all`` and both paths are bit-exact (pure data
movement, no arithmetic).  Mode selection reuses
``overlap.select_mode`` so ``PADDLE_TPU_OVERLAP`` and the cached probe
govern MoE dispatch exactly like the TP matmul ring.

`measured_ep_dispatch` drives the ring from the host (the
``measured_sharded_matmul`` pattern), emitting ``cat="collective"``
spans carrying ``axis="ep"`` whose lifetime brackets the in-flight hop
while the resident chunk's expert compute dispatches inside the window
— that is what ``observability.phase_breakdown()`` turns into
``overlap_ratio_ep``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import observability as obs
from ...ops.pallas_tiles import group_segments, num_group_blocks

__all__ = [
    "dropless_combine", "dropless_dispatch", "dropless_plan",
    "expert_imbalance", "measured_ep_dispatch", "ring_all_to_all_local",
]


# ---------------------------------------------------------------------------
# Dropless routing (single-device / inside one shard)
# ---------------------------------------------------------------------------

def dropless_plan(topk_idx, num_experts, block_rows, num_blocks=None):
    """Plan the grouped layout for top-k assignments — droplessly.

    ``topk_idx``: [N, k] int expert choices.  ``num_blocks`` must be
    the static `pallas_tiles.num_group_blocks(N * k, num_experts,
    block_rows)` (computed here when N is concrete).

    Returns ``(rows, block_group, counts)``:
      * ``rows``: [N * k] int32 — the grouped-buffer row of flat
        assignment ``n * k + j`` (rows are unique: scatter is exact);
      * ``block_group``: [num_blocks] int32 kernel descriptor
        (``num_experts`` = null block);
      * ``counts``: [num_experts] int32 tokens per expert (the
        imbalance/diagnostic gauge).

    Deterministic: the argsort is stable, so within one expert tokens
    keep their (token-major, then choice-major) arrival order.
    """
    N, k = topk_idx.shape
    T = N * k
    e_flat = topk_idx.reshape(-1).astype(jnp.int32)
    counts = jnp.zeros((num_experts,), jnp.int32).at[e_flat].add(1)
    if num_blocks is None:
        num_blocks = num_group_blocks(T, num_experts, block_rows)
    gid, offsets = group_segments(counts, block_rows, num_blocks)
    order = jnp.argsort(e_flat, stable=True)
    e_sorted = e_flat[order]
    csum = jnp.cumsum(counts) - counts                  # exclusive
    rank = jnp.arange(T, dtype=jnp.int32) - csum[e_sorted]
    rows = jnp.zeros((T,), jnp.int32).at[order].set(
        offsets[e_sorted] + rank)
    return rows, gid, counts


def dropless_dispatch(x, rows, top_k, padded_rows):
    """Scatter [N, D] tokens into the [padded_rows, D] grouped buffer:
    assignment ``n * k + j`` lands whole at ``rows[n * k + j]``;
    padding rows stay zero (the grouped kernel's contract)."""
    N, D = x.shape
    xr = jnp.repeat(x, top_k, axis=0)                   # [N*k, D]
    return jnp.zeros((padded_rows, D), x.dtype).at[rows].set(xr)


def dropless_combine(y_rows, rows, topk_val):
    """Gather expert outputs back and weighted-sum the k choices:
    ``y[n] = sum_j topk_val[n, j] * y_rows[rows[n*k+j]]`` (f32
    accumulation, cast back to the buffer dtype)."""
    N, k = topk_val.shape
    g = y_rows[rows].reshape(N, k, y_rows.shape[-1])
    return jnp.einsum(
        "nk,nkd->nd", topk_val.astype(jnp.float32),
        g.astype(jnp.float32)).astype(y_rows.dtype)


def expert_imbalance(counts):
    """Load-imbalance gauge: ``max(counts) / mean(counts)`` (1.0 =
    perfectly balanced; the bench gauge and the TPU508 threshold)."""
    c = jnp.asarray(counts, jnp.float32)
    return jnp.max(c) / jnp.maximum(jnp.mean(c), 1.0)


# ---------------------------------------------------------------------------
# Ring all-to-all (call inside shard_map)
# ---------------------------------------------------------------------------

def ring_all_to_all_local(x, *, axis, axis_size, mode="overlap"):
    """Per-shard tiled all-to-all on dim 0 through per-peer ``ppermute``
    hops (device ``i``'s chunk ``j`` lands at position ``i`` on device
    ``j`` — ``jax.lax.all_to_all(split=0, concat=0, tiled=True)``
    semantics, bit-exact: pure data movement).

    Overlapped mode issues one ``ppermute`` per peer offset; each hop
    is independent of the caller's subsequent compute on
    already-resident chunks, so XLA runs the transfers under the expert
    matmuls (the `overlap.all_gather_matmul_local` discipline).
    Sequential mode is the single fused collective.
    """
    P = int(axis_size)
    if P <= 1:
        return x
    if mode == "sequential":
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    C = x.shape[0] // P
    me = jax.lax.axis_index(axis)
    zero = jnp.zeros((), me.dtype)

    def chunk(i):
        idx = (i % P) * C
        return jax.lax.dynamic_slice(
            x, (idx,) + (zero,) * (x.ndim - 1), (C,) + x.shape[1:])

    out = jnp.zeros_like(x)
    # own chunk stays resident — no hop
    out = jax.lax.dynamic_update_slice(
        out, chunk(me), (me * C,) + (zero,) * (x.ndim - 1))
    for r in range(1, P):
        # peer-offset r: i sends its chunk (i+r) to device (i+r), where
        # it lands at source position (d-r); every hop is independent
        perm = [(i, (i + r) % P) for i in range(P)]
        recv = jax.lax.ppermute(chunk(me + r), axis, perm)
        out = jax.lax.dynamic_update_slice(
            out, recv, (((me - r) % P) * C,) + (zero,) * (x.ndim - 1))
    return out


# ---------------------------------------------------------------------------
# Measured host-driven ring (timeline evidence for overlap_ratio_ep)
# ---------------------------------------------------------------------------

#: (plan token, axis, shape, dtype) -> compiled one-hop rotation
_rot_cache: dict = {}


def _rot_fn(plan, axis, x):
    from ..jax_compat import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P
    key = (plan.cache_token(), axis, x.shape, str(x.dtype))
    fn = _rot_cache.get(key)
    if fn is not None:
        return fn
    size = plan.axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]
    spec = P(*((axis,) + (None,) * (x.ndim - 1)))
    rot = _shard_map(lambda v: jax.lax.ppermute(v, axis, perm),
                     mesh=plan.mesh, in_specs=spec, out_specs=spec)
    fn = jax.jit(rot).lower(x).compile()
    _rot_cache[key] = fn
    return fn


def measured_ep_dispatch(xd, expert_fn, *, plan, axis="ep", mode=None):
    """Drive the expert-dispatch ring step-wise from the host so the
    timeline records *real* ``axis="ep"`` collective spans.

    ``xd``: the global grouped token buffer, dim 0 sharded over
    ``axis`` (each of the P ring positions holds one chunk);
    ``expert_fn(xd)`` is the expert compute over the whole buffer (its
    Pallas path emits ``cat="kernel"`` spans).  Each of the P-1 ring
    hops is a compiled one-hop ``ppermute`` over the plan's mesh
    running inside a ``cat="collective"`` span carrying the ``ep`` axis
    attr; overlapped mode dispatches the resident chunks' expert
    compute while the hop is in flight — that nesting is what
    ``phase_breakdown()`` turns into ``overlap_ratio_ep``.  Sequential
    mode blocks on each hop first, so its ratio is ~0.  Step 0's
    compute over the un-rotated buffer is the real result (later
    steps' compute on rotated copies models the pipelined chunk
    arrival, exactly like ``measured_sharded_matmul``'s replicated
    partials).
    """
    from . import overlap as _overlap
    if plan is None or plan.is_virtual or plan.axis_size(axis) <= 1:
        raise ValueError("measured_ep_dispatch needs a real plan with "
                         f"axis {axis!r} > 1")
    if mode is None:
        mode = _overlap.select_mode(plan, axis)
    P = int(plan.axis_size(axis))
    xd = jnp.asarray(xd)
    rot = _rot_fn(plan, axis, xd)
    nb = int(xd.size) * xd.dtype.itemsize // P
    out = None
    cur = xd
    for r in range(P):
        if mode == "overlap" and r < P - 1:
            with obs.span("collective:moe.all_to_all", cat="collective",
                          axis=axis, bytes=nb, mode=mode, peers=P):
                nxt = rot(cur)
                with obs.span("dispatch:moe.expert_chunk",
                              cat="dispatch", axis=axis, mode=mode):
                    y = expert_fn(cur)
                    jax.block_until_ready(y)
                jax.block_until_ready(nxt)
        else:
            nxt = None
            if r < P - 1:
                with obs.span("collective:moe.all_to_all",
                              cat="collective", axis=axis, bytes=nb,
                              mode=mode, peers=P):
                    nxt = rot(cur)
                    jax.block_until_ready(nxt)
            with obs.span("dispatch:moe.expert_chunk", cat="dispatch",
                          axis=axis, mode=mode):
                y = expert_fn(cur)
                jax.block_until_ready(y)
        if r == 0:
            out = y
        if nxt is not None:
            cur = nxt
    return out
