"""SPMD sharding layer: partition rules -> PartitionSpec, MeshPlan.

This is the mesh-aware core that both execution tiers (the static
``Executor`` and ``jit.to_static``) compile against:

* ``match_partition_rules(rules, named_shapes)`` — fmengine-style regex
  matching of structural parameter names to ``PartitionSpec`` leaves.
  Scalar leaves are never sharded; a name matched by no rule raises.
* ``MeshPlan`` — the plan object.  Axes (``dp``/``tp``/``fsdp``/``pp``/
  ``ep``) come from a spec string such as ``"dp=4,tp=2"``
  (env: ``PADDLE_TPU_MESH``).
  It resolves rule hits into *legal* specs for a concrete shape (absent
  axes dropped, indivisible dims replicated), builds ``NamedSharding``s,
  and picks jit-with-NamedSharding vs ``shard_map`` per step function
  (``wrap_step``).
* ``annotate_params(layer)`` — stamps structural names from
  ``named_parameters()`` onto parameter tensors (``_spmd_name``) so the
  executor can match rules against real names instead of the
  auto-generated ``generated_tensor_N`` ids.
* ``shard_value`` / ``gather_value`` / ``make_shard_and_gather_fns`` —
  checkpoint save/load compatibility helpers.
* ``BERT_RULES`` / ``GPT_RULES`` — built-in rule sets for the bundled
  models (Megatron-style: column-parallel qkv/fc1, row-parallel
  out/fc2, fsdp over the remaining weight dim, embeddings over vocab).

The active plan is process-global: ``PADDLE_TPU_MESH`` selects one
lazily, ``set_mesh_plan`` overrides it programmatically.  Executable
caches key on ``plan_cache_token()`` so switching meshes never reuses a
stale executable.
"""
from __future__ import annotations

import math
import os
import re
import threading

import numpy as np

ENV_MESH = "PADDLE_TPU_MESH"

#: axes whose meaning is "replicas of the model" — the batch dimension
#: of feeds is sharded across these (fsdp shards params *and* batch).
DATA_AXES = ("dp", "fsdp")
MODEL_AXES = ("tp",)
#: stage axis: pipeline parallelism.  Not a sharding axis — partition
#: rules and batch specs never place tensors on it; it partitions the
#: *program* into stages (see auto_parallel.pipeline / stage_plan).
PIPELINE_AXES = ("pp",)
#: expert axis: MoE expert parallelism.  Stacked expert parameters
#: shard their leading [num_experts, ...] dim over it; token dispatch
#: crosses it with all-to-all (see distributed.moe).  Like tp it is a
#: model axis for batch purposes — feeds are never sharded over ep.
EXPERT_AXES = ("ep",)
KNOWN_AXES = DATA_AXES + MODEL_AXES + PIPELINE_AXES + EXPERT_AXES

__all__ = [
    "ENV_MESH", "DATA_AXES", "EXPERT_AXES", "MODEL_AXES", "KNOWN_AXES",
    "PIPELINE_AXES",
    "BERT_RULES", "GPT_RULES", "MOE_GPT_RULES", "MeshPlan",
    "annotate_params",
    "clear_mesh_plan", "gather_value", "gather_named", "get_mesh_plan",
    "make_shard_and_gather_fns", "match_partition_rules",
    "parse_mesh_spec", "plan_cache_token", "rules_for", "set_mesh_plan",
    "shard_value", "spmd_name",
]


def _pspec():
    from jax.sharding import PartitionSpec
    return PartitionSpec


def parse_mesh_spec(spec):
    """``"dp=4,tp=2"`` -> ``{"dp": 4, "tp": 2}`` (ordered, validated).
    ``;`` separates segments too (``"dp=4;pp=2"``) so the env knob
    composes with shell-quoted specs."""
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for part in str(spec).replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad mesh spec segment {part!r} in {spec!r}; "
                    f"expected axis=size, e.g. 'dp=4,tp=2'")
            name, _, size = part.partition("=")
            items.append((name.strip(), size.strip()))
    axes = {}
    for name, size in items:
        if name not in KNOWN_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r}; known axes: {KNOWN_AXES}")
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r} in {spec!r}")
        try:
            n = int(size)
        except (TypeError, ValueError):
            raise ValueError(
                f"mesh axis {name!r} has non-integer size {size!r}")
        if n < 1:
            raise ValueError(f"mesh axis {name!r} must be >= 1, got {n}")
        axes[name] = n
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def spmd_name(tensor):
    """Structural name for rule matching: ``_spmd_name`` if annotated
    (see :func:`annotate_params`), else the tensor's generated name."""
    return getattr(tensor, "_spmd_name", None) or getattr(
        tensor, "name", None) or ""


def annotate_params(layer, prefix=""):
    """Stamp structural names from ``named_parameters()`` onto the
    parameter tensors so partition rules can match them.

    Returns ``{structural_name: param}``.  Idempotent; safe to call on
    any ``nn.Layer`` before building the step program.
    """
    named = {}
    for name, p in layer.named_parameters():
        full = f"{prefix}{name}" if prefix else name
        try:
            p._spmd_name = full
        except AttributeError:
            pass
        named[full] = p
    return named


def _is_scalar_shape(shape):
    shape = tuple(shape)
    return len(shape) == 0 or math.prod(shape) <= 1


def match_partition_rules(rules, named_shapes):
    """Map structural names to raw ``PartitionSpec`` leaves via regex.

    ``rules`` is ``[(pattern, PartitionSpec)]``; the first pattern that
    ``re.search``-matches the name wins (fmengine semantics).  Scalar
    leaves (0-d, or a single element) are never sharded and skip
    matching entirely.  A non-scalar name matched by no rule raises
    ``ValueError`` — rule sets must be total (end with ``(".*", P())``
    to replicate everything else explicitly).

    ``named_shapes``: dict ``{name: shape}`` or iterable of
    ``(name, shape)``.  Returns ``{name: PartitionSpec}``.  The specs
    are the *raw* rule values; use ``MeshPlan.spec_for`` to legalise
    them against a concrete mesh and shape.
    """
    P = _pspec()
    if isinstance(named_shapes, dict):
        named_shapes = named_shapes.items()
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = {}
    for name, shape in named_shapes:
        if _is_scalar_shape(shape):
            out[name] = P()
            continue
        for pat, spec in compiled:
            if pat.search(name):
                out[name] = spec
                break
        else:
            raise ValueError(
                f"Partition rule not found for param: {name!r} "
                f"(shape {tuple(shape)}); add a rule or a catch-all "
                f"('.*', PartitionSpec())")
    return out


# ---------------------------------------------------------------------------
# Built-in rule sets for the bundled models.
#
# Weight layout note: ``nn.Linear`` stores weight as [in, out], so
# "column parallel" (split the output features) shards dim 1 over tp
# and "row parallel" (split the input features) shards dim 0 over tp.
# fsdp takes whichever weight dim tp does not.  On a mesh without an
# axis named in a spec, MeshPlan.spec_for drops that axis, so one rule
# set serves dp-only, tp-only, fsdp-only and mixed meshes.
# ---------------------------------------------------------------------------

def _P(*args):
    return _pspec()(*args)


def BERT_RULES():
    """Partition rules for the bundled BERT models (structural names
    like ``bert.encoder.0.attention.qkv.weight``)."""
    return [
        (r"word_embeddings\.weight$", _P("tp", "fsdp")),
        (r"(position|token_type)_embeddings\.weight$", _P(None, "fsdp")),
        (r"attention\.qkv\.weight$", _P("fsdp", "tp")),
        (r"attention\.qkv\.bias$", _P("tp")),
        (r"attention\.out\.weight$", _P("tp", "fsdp")),
        (r"fc1\.weight$", _P("fsdp", "tp")),
        (r"fc1\.bias$", _P("tp")),
        (r"fc2\.weight$", _P("tp", "fsdp")),
        (r"cls\.transform\.weight$", _P("fsdp", None)),
        (r"pooler\.dense\.weight$", _P("fsdp", None)),
        (r"(ln|ln1|ln2|layer_norm)\.(weight|bias)$", _P()),
        (r"bias$", _P()),
        (r".*", _P()),
    ]


def GPT_RULES():
    """Partition rules for the bundled GPT models (structural names
    like ``gpt.h.0.attn.qkv_proj.weight``)."""
    return [
        (r"wte\.weight$", _P("tp", "fsdp")),
        (r"wpe\.weight$", _P(None, "fsdp")),
        (r"attn\.qkv_proj\.weight$", _P("fsdp", "tp")),
        (r"attn\.qkv_proj\.bias$", _P("tp")),
        (r"attn\.out_proj\.weight$", _P("tp", "fsdp")),
        (r"mlp\.fc1\.weight$", _P("fsdp", "tp")),
        (r"mlp\.fc1\.bias$", _P("tp")),
        (r"mlp\.fc2\.weight$", _P("tp", "fsdp")),
        (r"lm_head\.weight$", _P("fsdp", "tp")),
        (r"(ln_1|ln_2|ln_f|ln)\.(weight|bias)$", _P()),
        (r"bias$", _P()),
        (r".*", _P()),
    ]


def MOE_GPT_RULES():
    """Partition rules for the bundled MoE GPT (``models/moe_gpt.py``):
    the stacked expert weights [E, ...] shard their expert dim over
    ``ep`` (dropped automatically on meshes without one); the router
    stays replicated so every device ranks every expert; the shared
    trunk follows ``GPT_RULES``."""
    return [
        (r"mlp\.router$", _P()),
        (r"mlp\.w[12]$", _P("ep", None, None)),
        (r"mlp\.b[12]$", _P("ep", None)),
    ] + GPT_RULES()


_BUILTIN_RULES = {"bert": BERT_RULES, "gpt": GPT_RULES,
                  "moe_gpt": MOE_GPT_RULES}


def rules_for(model):
    """Built-in rule set by model family name ('bert' or 'gpt')."""
    try:
        return _BUILTIN_RULES[model.lower()]()
    except KeyError:
        raise ValueError(
            f"no built-in partition rules for {model!r}; "
            f"known: {sorted(_BUILTIN_RULES)}")


class MeshPlan:
    """A named device mesh + partition rules = how a step function is
    compiled and laid out.

    ``spec``: mesh axes, e.g. ``"dp=4,tp=2"`` (string or dict).
    ``rules``: ``[(regex, PartitionSpec)]`` partition rules for named
    parameters; empty/None means every parameter is replicated (pure
    data parallelism).
    ``virtual=True`` builds a plan without a jax ``Mesh`` — rule
    resolution and per-device byte math still work (used by tpu_lint on
    single-device hosts), but anything needing real devices raises.
    """

    def __init__(self, spec, rules=None, devices=None, virtual=False):
        self.axis_sizes = parse_mesh_spec(spec)
        self.axis_names = tuple(self.axis_sizes)
        self.rules = list(rules) if rules else []
        self.size = math.prod(self.axis_sizes.values())
        self._mesh = None
        self._virtual = bool(virtual)
        # Bumped by shrink(): keeps executable-cache keys fresh across a
        # recovery even when the shrunk topology coincides with an old one.
        self._generation = 0
        self.shrink_findings = []
        if not virtual:
            import jax
            devs = list(devices) if devices is not None else jax.devices()
            if self.size > len(devs):
                raise ValueError(
                    f"mesh {self.describe()!r} needs {self.size} devices "
                    f"but only {len(devs)} are visible; set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N for a "
                    f"host mesh, or shrink {ENV_MESH}")
            from jax.sharding import Mesh
            arr = np.asarray(devs[: self.size]).reshape(
                tuple(self.axis_sizes.values()))
            self._mesh = Mesh(arr, self.axis_names)

    # -- identity ---------------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            raise RuntimeError(
                f"MeshPlan({self.describe()!r}) is virtual (no devices); "
                "rebuild with virtual=False on a host with enough devices")
        return self._mesh

    @property
    def is_virtual(self):
        return self._virtual

    def axis_size(self, name):
        return self.axis_sizes.get(name, 1)

    def describe(self):
        return ",".join(f"{k}={v}" for k, v in self.axis_sizes.items())

    def rules_token(self):
        return tuple((pat, str(spec)) for pat, spec in self.rules)

    def cache_token(self):
        """Hashable token identifying mesh topology + rule set + the
        configured collective-overlap mode; mixed into executable-cache
        keys so plans never share executables.  The pp axis enters via
        ``axis_sizes``; the overlap mode via ``overlap.mode_token()``."""
        from . import overlap as _overlap
        return (tuple(self.axis_sizes.items()), self.rules_token(),
                _overlap.mode_token(), self._generation)

    def __repr__(self):
        return (f"MeshPlan({self.describe()}, rules={len(self.rules)}"
                f"{', virtual' if self._virtual else ''})")

    # -- pipeline stages --------------------------------------------------
    @property
    def num_stages(self):
        """Pipeline depth: size of the ``pp`` axis (1 = no pipeline)."""
        return self.axis_sizes.get("pp", 1)

    def stage_plan(self, stage):
        """The sub-plan one pipeline stage computes under.

        Slices this plan's device array along the ``pp`` axis and
        rebuilds a MeshPlan over the remaining axes (same rules), so a
        stage's step function compiles and shards exactly like a
        non-pipelined program on its device subset.  Returns ``None``
        when nothing but ``pp`` (or nothing at all) remains — the stage
        runs as a plain jitted function on its slice's first device.
        """
        stages = self.num_stages
        if not 0 <= stage < stages:
            raise ValueError(f"stage {stage} out of range for "
                             f"pp={stages}")
        rest = {a: n for a, n in self.axis_sizes.items()
                if a != "pp" and n > 1}
        if "pp" not in self.axis_sizes:
            return self if stage == 0 else None
        if self._virtual:
            return MeshPlan(rest, rules=self.rules, virtual=True) \
                if rest else None
        arr = np.asarray(self.mesh.devices)
        idx = self.axis_names.index("pp")
        devs = list(np.take(arr, [stage], axis=idx).ravel())
        if not rest:
            return None
        return MeshPlan(rest, rules=self.rules, devices=devs)

    def stage_devices(self, stage):
        """Devices backing one pipeline stage (row of the pp axis)."""
        arr = np.asarray(self.mesh.devices)
        if "pp" not in self.axis_sizes:
            return list(arr.ravel())
        idx = self.axis_names.index("pp")
        return list(np.take(arr, [stage], axis=idx).ravel())

    # -- elastic recovery -------------------------------------------------
    def shrink(self, surviving_devices):
        """Rebuild this plan over a smaller device set after a loss.

        dp is the preferred shrink axis: it drops to the largest divisor
        of the original dp size that still fits, so global-batch
        divisibility (and therefore bit-identical resume on the shrunk
        mesh) is preserved.  Model-parallel axes that no longer fit
        (tp, then fsdp, then pp, then ep — ep=1 keeps every expert
        resident on every device) fall back to replication — each drop is
        recorded as a TPU505 finding on ``shrink_findings`` and in the
        diagnostic log.  The new plan reuses the SAME partition rules,
        so ``_legalize`` re-materializes specs on the smaller mesh, and
        carries a bumped ``_generation`` so ``cache_token()`` never
        collides with a pre-loss executable cache entry.
        """
        from ...analysis import diagnostics as _diag
        if self._virtual:
            raise RuntimeError("cannot shrink a virtual MeshPlan")
        devs = list(surviving_devices)
        if not devs:
            raise ValueError("shrink() needs at least one surviving device")
        axes = dict(self.axis_sizes)
        findings = []

        def _non_dp():
            return math.prod(v for k, v in axes.items() if k != "dp")

        for ax in ("tp", "fsdp", "pp", "ep"):
            if _non_dp() <= len(devs):
                break
            if axes.get(ax, 1) > 1:
                msg = (f"mesh shrink {self.describe()} -> {len(devs)} "
                       f"devices: axis {ax}={axes[ax]} no longer fits; "
                       f"its parameters fall back to replication")
                findings.append(_diag.record(_diag.Diagnostic(
                    "TPU505", msg, site=f"mesh.shrink.{ax}",
                    hint="restore capacity or re-launch with a smaller "
                         f"{ax} degree to re-shard these parameters",
                    data={"axis": ax, "old_size": axes[ax],
                          "surviving": len(devs)})))
                axes[ax] = 1
        if _non_dp() > len(devs):
            raise ValueError(
                f"cannot shrink {self.describe()} onto {len(devs)} "
                f"devices: model-parallel axes need {_non_dp()}")
        old_dp = axes.get("dp", 1)
        cap = len(devs) // _non_dp()
        new_dp = max(d for d in range(1, old_dp + 1)
                     if old_dp % d == 0 and d <= cap)
        if "dp" in axes:
            axes["dp"] = new_dp
        new = MeshPlan(axes, rules=self.rules, devices=devs)
        new._generation = self._generation + 1
        new.shrink_findings = findings
        return new

    # -- spec resolution --------------------------------------------------
    def data_axes(self):
        """Mesh axes the feed batch dimension is sharded over."""
        return tuple(a for a in DATA_AXES
                     if self.axis_sizes.get(a, 1) > 1)

    def data_parallel_size(self):
        return math.prod(self.axis_sizes.get(a, 1) for a in DATA_AXES)

    def _legalize(self, raw_spec, shape):
        """Clamp a raw rule spec to a concrete shape on this mesh:
        absent/size-1 axes dropped, indivisible dims replicated, an
        axis used at most once across the spec."""
        P = _pspec()
        shape = tuple(shape)
        if _is_scalar_shape(shape):
            return P()
        entries = tuple(raw_spec)[: len(shape)]
        used, out = set(), []
        for dim, entry in zip(shape, tuple(entries) + (None,) * len(shape)):
            if entry is None:
                out.append(None)
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            names = tuple(n for n in names
                          if self.axis_sizes.get(n, 1) > 1 and n not in used)
            factor = math.prod(self.axis_sizes[n] for n in names)
            if factor <= 1 or dim % factor != 0:
                out.append(None)
                continue
            used.update(names)
            out.append(names if len(names) > 1 else names[0])
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def match(self, name, shape):
        """Lenient rule lookup: ``(matched, legal_spec)``.

        Scalars are always ``(True, P())``.  With no rules, everything
        is ``(True, P())`` (replicated — pure DP).  A rule miss returns
        ``(False, P())`` instead of raising so the executor can shard
        what it knows and lint the rest (TPU501).
        """
        P = _pspec()
        shape = tuple(shape)
        if _is_scalar_shape(shape) or not self.rules:
            return True, P()
        for pat, spec in self._compiled_rules():
            if pat.search(name):
                return True, self._legalize(spec, shape)
        return False, P()

    def _compiled_rules(self):
        cached = getattr(self, "_rules_compiled", None)
        if cached is None:
            cached = [(re.compile(pat), spec) for pat, spec in self.rules]
            self._rules_compiled = cached
        return cached

    def spec_for(self, name, shape):
        return self.match(name, shape)[1]

    def specs_for(self, named_shapes):
        if isinstance(named_shapes, dict):
            named_shapes = named_shapes.items()
        return {name: self.spec_for(name, shape)
                for name, shape in named_shapes}

    def batch_spec(self, shape):
        """Spec for a feed/activation: dim 0 sharded over the data
        axes when divisible, otherwise fully replicated."""
        P = _pspec()
        shape = tuple(shape)
        axes = self.data_axes()
        if not axes or not shape or _is_scalar_shape(shape):
            return P()
        factor = math.prod(self.axis_sizes[a] for a in axes)
        if shape[0] % factor != 0:
            return P()
        return P(axes if len(axes) > 1 else axes[0])

    # -- shardings --------------------------------------------------------
    def sharding(self, spec=None):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, spec if spec is not None
                             else _pspec()())

    def replicated(self):
        return self.sharding(_pspec()())

    def tree_shardings(self, spec_tree):
        """Map a pytree of PartitionSpec leaves to NamedShardings."""
        import jax
        P = _pspec()
        return jax.tree_util.tree_map(
            lambda s: self.sharding(s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    # -- per-device memory math ------------------------------------------
    def shard_factor(self, spec):
        """How many ways a spec splits a buffer across the mesh."""
        if spec is None:
            return 1
        factor = 1
        for entry in tuple(spec):
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            for n in names:
                factor *= self.axis_sizes.get(n, 1)
        return max(1, factor)

    def per_device_nbytes(self, nbytes, spec):
        """Bytes one device holds for a buffer laid out as ``spec``:
        sharded residents divide by the axis-size product, replicated
        buffers are charged whole."""
        return int(nbytes) // self.shard_factor(spec)

    # -- step-function compilation ---------------------------------------
    def wrap_step(self, fn, in_shardings=None, out_shardings=None,
                  in_specs=None, out_specs=None, donate_argnums=(),
                  static_argnums=(), **jit_kwargs):
        """Compile a step function for this mesh.

        Two modes (Titanax semantics — explicit shardings mean GSPMD,
        map-style specs mean per-shard SPMD):

        * ``in_shardings``/``out_shardings`` given (pytrees of
          ``PartitionSpec`` or ``NamedSharding``): ``jax.jit`` with
          NamedShardings — the partitioner inserts collectives.
        * ``in_specs``/``out_specs`` given: ``shard_map`` over the
          mesh — ``fn`` sees per-shard arrays and writes its own
          collectives (``jax.lax.p*`` over the axis names).
        * neither: plain ``jax.jit`` under this mesh's context so
          ``with_sharding_constraint`` inside ``fn`` resolves.
        """
        import jax
        from jax.sharding import NamedSharding
        P = _pspec()
        if in_specs is not None or out_specs is not None:
            if in_shardings is not None or out_shardings is not None:
                raise ValueError(
                    "pass either in_/out_shardings (jit) or "
                    "in_/out_specs (shard_map), not both")
            from jax.experimental.shard_map import shard_map
            mapped = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                               out_specs=out_specs)
            return jax.jit(mapped, donate_argnums=donate_argnums,
                           static_argnums=static_argnums, **jit_kwargs)
        is_leaf = lambda x: isinstance(x, (P, NamedSharding))  # noqa: E731
        to_ns = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda s: s if isinstance(s, NamedSharding) else self.sharding(s),
            t, is_leaf=is_leaf)
        if in_shardings is not None:
            jit_kwargs["in_shardings"] = to_ns(in_shardings)
        if out_shardings is not None:
            jit_kwargs["out_shardings"] = to_ns(out_shardings)
        return jax.jit(fn, donate_argnums=donate_argnums,
                       static_argnums=static_argnums, **jit_kwargs)

    # -- placement --------------------------------------------------------
    def place(self, value, spec):
        """``device_put`` a host or device array under ``spec``."""
        import jax
        return jax.device_put(value, self.sharding(spec))


# ---------------------------------------------------------------------------
# Checkpoint shard/gather helpers
# ---------------------------------------------------------------------------

def shard_value(value, plan, spec):
    """Place a (host) value onto the plan's mesh under ``spec``."""
    return plan.place(value, spec)


def gather_value(value):
    """Full host ``np.ndarray`` from a (possibly sharded) jax array.

    Works for any fully-addressable array — single-controller meshes
    (the only kind this repo builds) always are.
    """
    try:
        return np.asarray(value)
    except Exception:
        import jax
        gathered = jax.device_get(value)
        return np.asarray(gathered)


def gather_named(named_tensors):
    """``{name: tensor}`` (or ``[(name, tensor)]``) -> ``{name: np}``,
    gathering every shard to the host — checkpoint-save compatible."""
    if isinstance(named_tensors, dict):
        named_tensors = named_tensors.items()
    out = {}
    for name, t in named_tensors:
        val = getattr(t, "_value", t)
        out[name] = gather_value(val)
    return out


def make_shard_and_gather_fns(plan, named_shapes):
    """fmengine-style helper: per-name ``shard_fn(host_array)`` /
    ``gather_fn(device_array)`` pairs for checkpoint save/load."""
    specs = plan.specs_for(named_shapes)

    def _shard_fn(spec):
        return lambda x: plan.place(x, spec)

    shard_fns = {name: _shard_fn(spec) for name, spec in specs.items()}
    gather_fns = {name: gather_value for name in specs}
    return shard_fns, gather_fns


# ---------------------------------------------------------------------------
# Process-global active plan
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_override = None          # plan set programmatically (or explicit None)
_override_set = False
_env_cache = {}           # env string -> MeshPlan


def set_mesh_plan(plan):
    """Set (or with ``None`` clear back to env-driven) the active plan."""
    global _override, _override_set
    with _lock:
        _override = plan
        _override_set = plan is not None


def clear_mesh_plan():
    global _override, _override_set
    with _lock:
        _override = None
        _override_set = False
        _env_cache.clear()


def get_mesh_plan():
    """Active :class:`MeshPlan`, or ``None`` when unsharded.

    Programmatic ``set_mesh_plan`` wins; otherwise ``PADDLE_TPU_MESH``
    (e.g. ``dp=4,tp=2``) lazily builds one over the visible devices.
    A mesh of total size 1 means "not sharded" and yields ``None``.
    """
    with _lock:
        if _override_set:
            return _override
    env = os.environ.get(ENV_MESH, "").strip()
    if not env:
        return None
    with _lock:
        plan = _env_cache.get(env)
        if plan is None:
            plan = MeshPlan(env)
            _env_cache[env] = plan
    return plan if plan.size > 1 else None


def plan_cache_token():
    """Token for executable-cache keys: ``None`` when unsharded."""
    plan = get_mesh_plan()
    return None if plan is None else plan.cache_token()
