"""TCPStore: the rendezvous key-value store.

Reference parity: `fluid/distributed/store/tcp_store.cc` +
`paddle.distributed.TCPStore` (master rank hosts the store; every rank
connects for set/get/add/wait/barrier during init_parallel_env
[UNVERIFIED — empty reference mount; SURVEY.md §2.1 "Comm runtime"]).

TPU-native split: ICI/DCN collectives never touch this store (XLA owns
them); what remains is host-side rendezvous — and that part is the
reference's design unchanged.  The SERVER is native C++
(`_native/tcp_store.cc`: thread-per-connection over a cv-guarded map,
blocking GET/WAIT park the caller server-side), built on first use; a
pure-python server is the fallback when no C++ toolchain exists.  The
client speaks the length-prefixed wire protocol over one socket.

Hardening (fault_tolerance layer):
  * connect phase: exponential backoff with deterministic jitter — the
    master binding late (the startup race) no longer fails rank N hard
    on the first ECONNREFUSED;
  * per-op deadlines: the client socket carries ``timeout`` via
    ``settimeout``, so a dead server turns a blocking get into a named
    TimeoutError instead of an eternal hang;
  * bounded replay: idempotent ops (get/query/wait/num_keys) reconnect
    and retry up to ``PADDLE_TPU_STORE_RETRIES`` times on transient
    socket errors (a store restart mid-rendezvous is survivable);
  * ``fault_point("store.connect")`` / ``("store.<op>")`` sites let the
    FaultPlan drop or delay any of this deterministically.

Control-plane resilience (PR 17):
  * ``LocalStore`` — the in-process dict stand-in (moved here from
    serving/cluster.py) with TCPStore-parity ``wait(keys, deadline=)``
    semantics: it blocks, and raises the same structured
    ``StoreTimeoutError``;
  * ``ResilientStore`` — an outage-surviving wrapper that owns the live
    ``_PyStoreServer`` master, promotes a standby on master death
    (clients reconnect through the existing RetryPolicy), stamps every
    promotion with a monotonic **store epoch**, and fences any write
    carrying a stale-epoch ``StoreLease`` with a structured
    ``StoreEpochError`` — split-brain protection on top of the fabric's
    ``(request_id, commit_gen, export_seq)`` idempotency keys.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

from .. import observability as obs
from .fault_tolerance.plan import fault_point
from .fault_tolerance.retry import (ENV_STORE_RETRIES,
                                    RetryExhausted, RetryPolicy)

__all__ = ["StoreTimeoutError", "StoreEpochError", "StoreLease",
           "LocalStore", "ResilientStore", "TCPStore"]


class StoreTimeoutError(TimeoutError):
    """``TCPStore.wait`` ran out its hard deadline.

    Structured: ``keys`` is the full wait set, ``pending`` the keys
    not yet observed when the deadline hit, ``waited_s`` the wall time
    actually spent, ``deadline_s`` the budget.  Subclasses
    ``TimeoutError`` so pre-existing ``except TimeoutError`` callers
    keep working."""

    def __init__(self, msg, keys=(), pending=(), waited_s=0.0,
                 deadline_s=0.0):
        super().__init__(msg)
        self.keys = tuple(keys)
        self.pending = tuple(pending)
        self.waited_s = float(waited_s)
        self.deadline_s = float(deadline_s)


class _PyStoreServer:
    """Python fallback server implementing the same wire protocol."""

    def __init__(self, port=0):
        self._data = {}
        self._cv = threading.Condition()
        self._stop = False
        self._srv = socket.create_server(("0.0.0.0", port))
        self.port = self._srv.getsockname()[1]
        self._conns = set()
        self._conn_lock = threading.Lock()
        self._workers = []
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            if self._stop:  # woken by stop()'s self-connect
                conn.close()
                break
            with self._conn_lock:
                self._conns.add(conn)
                # reap finished workers so a long-lived server doesn't
                # accumulate dead Thread objects
                self._workers = [t for t in self._workers if t.is_alive()]
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._workers.append(t)

    def _read_n(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                cmd = self._read_n(conn, 1)
                if cmd == b"X":
                    self.stop()
                    return
                if cmd == b"N":
                    with self._cv:
                        n = len(self._data)
                    conn.sendall(struct.pack("<q", n))
                    continue
                (klen,) = struct.unpack("<I", self._read_n(conn, 4))
                key = self._read_n(conn, klen).decode()
                if cmd == b"S":
                    (vlen,) = struct.unpack("<Q", self._read_n(conn, 8))
                    val = self._read_n(conn, vlen) if vlen else b""
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif cmd in (b"G", b"W"):
                    with self._cv:
                        while key not in self._data and not self._stop:
                            self._cv.wait(0.1)
                        val = self._data.get(key, b"")
                    if self._stop and key not in self._data:
                        return
                    if cmd == b"W":
                        conn.sendall(b"\x01")
                    else:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"Q":
                    with self._cv:
                        has = key in self._data
                        val = self._data.get(key, b"")
                    conn.sendall(b"\x01" if has else b"\x00")
                    if has:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"A":
                    (amt,) = struct.unpack("<q", self._read_n(conn, 8))
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._data.get(
                                key, b"\0" * 8))[0] if len(
                            self._data.get(key, b"\0" * 8)) == 8 else 0
                        now = cur + amt
                        self._data[key] = struct.pack("<q", now)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", now))
                elif cmd == b"D":
                    with self._cv:
                        self._data.pop(key, None)
                    conn.sendall(b"\x01")
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conn_lock:
                self._conns.discard(conn)

    def stop(self):
        if self._stop:
            return
        self._stop = True
        try:
            # closing the listener does NOT interrupt a blocked accept()
            # on Linux — poke it awake so the accept thread can exit
            with socket.create_connection(("127.0.0.1", self.port),
                                          timeout=0.5):
                pass
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._cv:
            self._cv.notify_all()
        # closing live connections unblocks workers parked in recv()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=2)
        for t in self._workers:
            if t is not threading.current_thread():
                t.join(timeout=1)

    close = stop

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """paddle.distributed.TCPStore-compatible client (+ server on the
    master rank).

    TCPStore(host, port, is_master=False, world_size=1, timeout=...)
    with set/get/add/wait/delete_key/num_keys/barrier.  ``timeout``
    bounds the connect phase, every single op (via socket.settimeout),
    and barrier(); ``retries`` (default ``PADDLE_TPU_STORE_RETRIES``,
    3) bounds the replay of idempotent ops across reconnects.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=300, retries=None, **kwargs):
        self._host = host
        self._world_size = world_size
        self._timeout = float(timeout)
        self._retries = int(os.environ.get(ENV_STORE_RETRIES, "3")) \
            if retries is None else int(retries)
        self._server = None
        self._native_handle = None
        if is_master:
            from .._native import (tcp_store_available,
                                   start_tcp_store_server)
            if tcp_store_available():
                self._native_handle, port = \
                    start_tcp_store_server(port)
            else:
                self._server = _PyStoreServer(port)
                port = self._server.port
        self.port = port
        self._sock = None
        self._lock = threading.Lock()
        # per-call replay schedule for idempotent ops: a FRESH delay
        # sequence every call (a shared generator would saturate at
        # max_delay after the first few retries and stay there forever)
        self._op_policy = RetryPolicy(retries=self._retries, base=0.02,
                                      factor=2.0, max_delay=0.5)
        with self._lock:
            self._connect()

    # -- wire ------------------------------------------------------------
    def _connect(self):
        """Connect with exponential backoff + jitter until ``timeout``:
        the master rank binding late (startup race) is expected, not
        fatal."""

        def attempt():
            fault_point("store.connect")
            try:
                self._sock = socket.create_connection(
                    (self._host, self.port),
                    timeout=min(self._timeout, 5.0))
            except OSError:
                self._sock = None
                raise
            # per-op deadline: every later recv/send on this socket
            # fails with TimeoutError instead of hanging forever
            self._sock.settimeout(self._timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)

        policy = RetryPolicy(retries=None, base=0.05, factor=1.6,
                             max_delay=1.0)
        try:
            policy.call(attempt, exceptions=(OSError,),
                        deadline=time.monotonic() + self._timeout,
                        what="store.connect")
        except RetryExhausted as e:
            raise TimeoutError(
                f"TCPStore: cannot reach {self._host}:{self.port} "
                f"within {self._timeout}s (last error: {e.last})") \
                from e.last

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("TCPStore server closed")
            buf += chunk
        return buf

    def _req(self, cmd, key=None, payload=b""):
        msg = cmd
        if key is not None:
            kb = key.encode()
            msg += struct.pack("<I", len(kb)) + kb
        msg += payload
        self._sock.sendall(msg)

    def _call(self, op_name, fn, idempotent=False, deadline=None):
        """Run one wire op under the lock.  Transient socket errors
        drop the connection; idempotent ops reconnect and replay through
        ``RetryPolicy`` (the store may have restarted — get/wait/query
        replay safely; set/add/delete never do).  A reply *timeout* is
        never replayed: the stream is desynced, so the socket is
        poisoned and the error surfaces immediately."""

        class _ReplyTimeout(Exception):
            pass  # not an OSError: opts out of the replay policy

        def attempt():
            with self._lock:
                try:
                    if self._sock is None:
                        self._connect()
                    fault_point("store." + op_name)
                    return fn()
                except TimeoutError as e:
                    # reply stream is now desynced: poison the socket so
                    # the next op reconnects cleanly
                    self._drop_sock()
                    raise _ReplyTimeout() from e
                except (ConnectionError, OSError):
                    self._drop_sock()
                    raise

        try:
            if idempotent:
                return self._op_policy.call(
                    attempt, exceptions=(ConnectionError, OSError),
                    deadline=deadline, what="store." + op_name)
            return attempt()
        except _ReplyTimeout as e:
            raise TimeoutError(
                f"TCPStore {op_name!r}: no reply within "
                f"{self._timeout}s from "
                f"{self._host}:{self.port}") from e.__cause__
        except RetryExhausted as e:
            raise ConnectionError(
                f"TCPStore {op_name!r}: {self._retries + 1} attempt(s) "
                f"failed against {self._host}:{self.port} "
                f"(last error: {e.last})") from e.last

    # -- API -------------------------------------------------------------
    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        value = bytes(value)

        def fn():
            self._req(b"S", key, struct.pack("<Q", len(value)) + value)
            self._read_n(1)
        self._call("set", fn)

    def get(self, key):
        """Blocking get (waits until the key exists, up to timeout)."""
        def fn():
            self._req(b"G", key)
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""
        return self._call("get", fn, idempotent=True)

    def query(self, key):
        """Non-blocking get: returns None when absent."""
        def fn():
            self._req(b"Q", key)
            has = self._read_n(1) == b"\x01"
            if not has:
                return None
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""
        return self._call("query", fn, idempotent=True)

    def add(self, key, amount=1):
        def fn():
            self._req(b"A", key, struct.pack("<q", int(amount)))
            (now,) = struct.unpack("<q", self._read_n(8))
            return now
        return self._call("add", fn)

    def wait(self, keys, deadline=None):
        """Block until every key exists — under a HARD deadline.

        ``deadline`` (seconds; default the store timeout) bounds the
        WHOLE wait: all keys, all reconnect retries (paced by the
        ``RetryPolicy``, which stops scheduling attempts past the
        deadline), and each server-side park (the socket timeout is
        shrunk to the remaining budget, so a wedged master cannot
        spin this past its bound).  On expiry raises
        :class:`StoreTimeoutError` naming the pending keys and emits
        a ``store.wait_timeout`` instant."""
        if isinstance(keys, str):
            keys = [keys]
        keys = list(keys)
        budget = self._timeout if deadline is None else float(deadline)
        t_end = time.monotonic() + budget

        def _expired(err, pending):
            waited = budget - max(0.0, t_end - time.monotonic())
            obs.instant("store.wait_timeout", cat="fault",
                        keys=len(keys), pending=pending[0],
                        waited_s=round(waited, 3),
                        deadline_s=round(budget, 3))
            raise StoreTimeoutError(
                f"TCPStore.wait: {len(pending)}/{len(keys)} key(s) "
                f"still absent after {waited:.3f}s "
                f"(deadline {budget:.3f}s); first pending: "
                f"{pending[0]!r}", keys=keys, pending=pending,
                waited_s=waited, deadline_s=budget) from err

        for n, k in enumerate(keys):
            def fn(k=k):
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"wait deadline expired before {k!r}")
                prev = self._sock.gettimeout()
                self._sock.settimeout(
                    min(prev, remaining) if prev else remaining)
                try:
                    self._req(b"W", k)
                    self._read_n(1)
                finally:
                    try:
                        self._sock.settimeout(prev)
                    except OSError:
                        pass
            try:
                self._call("wait", fn, idempotent=True, deadline=t_end)
            except (TimeoutError, ConnectionError) as e:
                _expired(e, keys[n:])

    def delete_key(self, key):
        def fn():
            self._req(b"D", key)
            self._read_n(1)
        self._call("delete_key", fn)
        return True

    def num_keys(self):
        def fn():
            self._req(b"N")
            (n,) = struct.unpack("<q", self._read_n(8))
            return n
        return self._call("num_keys", fn, idempotent=True)

    def barrier(self, tag="barrier"):
        """All world_size ranks block until everyone arrived."""
        n = self.add(f"__{tag}__", 1)
        round_ = (n - 1) // self._world_size
        target = (round_ + 1) * self._world_size
        deadline = time.monotonic() + self._timeout
        while self.add(f"__{tag}__", 0) < target:
            if time.monotonic() > deadline:
                raise TimeoutError(f"TCPStore barrier {tag!r} timed out")
            time.sleep(0.002)

    def close(self):
        with self._lock:
            self._drop_sock()
        if self._native_handle is not None:
            from .._native import stop_tcp_store_server
            stop_tcp_store_server(self._native_handle)
            self._native_handle = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass


class StoreEpochError(RuntimeError):
    """A store write carried a lease stamped with a stale epoch.

    Raised BEFORE the write touches the store: the lease holder was
    fenced out by a standby promotion (its epoch predates the store's),
    so letting the write land could double-own a request across a
    partition.  Structured: ``lease_epoch`` / ``store_epoch`` are the
    two epochs, ``owner`` the lease holder, ``key`` the refused key."""

    def __init__(self, msg, *, lease_epoch=0, store_epoch=0, owner="",
                 key=""):
        super().__init__(msg)
        self.lease_epoch = int(lease_epoch)
        self.store_epoch = int(store_epoch)
        self.owner = str(owner)
        self.key = str(key)


class StoreLease:
    """An epoch-stamped write capability handed out by ResilientStore.

    Immutable: renewing after a promotion returns a NEW lease at the
    current epoch (``ResilientStore.renew``) — a fenced-out holder can
    never un-fence a stale one in place."""

    __slots__ = ("owner", "epoch")

    def __init__(self, owner, epoch):
        self.owner = str(owner)
        self.epoch = int(epoch)

    def __repr__(self):
        return f"StoreLease(owner={self.owner!r}, epoch={self.epoch})"


class LocalStore:
    """In-process dict stand-in for :class:`TCPStore` (single-host
    clusters, loopback-transport tests).

    Parity contract (PR 17 satellite): ``wait(keys, deadline=)`` blocks
    and raises the same structured :class:`StoreTimeoutError` (with the
    ``store.wait_timeout`` instant) as ``TCPStore.wait`` — loopback
    tests exercise the identical timeout path as the real fabric.
    Counters are stored as ASCII digits, matching what gossip/transport
    code round-trips through a real store."""

    def __init__(self, timeout=5.0):
        self._cv = threading.Condition()
        self._data = {}
        self._timeout = float(timeout)

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        with self._cv:
            self._data[key] = value
            self._cv.notify_all()

    def get(self, key):
        """Blocking get (waits until the key exists, up to timeout)."""
        t_end = time.monotonic() + self._timeout
        with self._cv:
            while key not in self._data:
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"LocalStore.get: no value for {key!r} within "
                        f"{self._timeout:.3f}s")
                self._cv.wait(min(remaining, 0.05))
            return self._data[key]

    def query(self, key):
        """Non-blocking get: returns None when absent."""
        with self._cv:
            return self._data.get(key)

    def add(self, key, amount=1):
        with self._cv:
            now = int(self._data.get(key, b"0")) + int(amount)
            self._data[key] = str(now).encode()
            self._cv.notify_all()
            return now

    def wait(self, keys, deadline=None):
        """Block until every key exists — under a HARD deadline, with
        ``TCPStore.wait``'s exact failure shape."""
        if isinstance(keys, str):
            keys = [keys]
        keys = list(keys)
        budget = self._timeout if deadline is None else float(deadline)
        t_end = time.monotonic() + budget
        with self._cv:
            pending = [k for k in keys if k not in self._data]
            while pending:
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    waited = budget
                    obs.instant("store.wait_timeout", cat="fault",
                                keys=len(keys), pending=pending[0],
                                waited_s=round(waited, 3),
                                deadline_s=round(budget, 3))
                    raise StoreTimeoutError(
                        f"LocalStore.wait: {len(pending)}/{len(keys)} "
                        f"key(s) still absent after {waited:.3f}s "
                        f"(deadline {budget:.3f}s); first pending: "
                        f"{pending[0]!r}", keys=keys, pending=pending,
                        waited_s=waited, deadline_s=budget)
                self._cv.wait(min(remaining, 0.05))
                pending = [k for k in keys if k not in self._data]

    def delete_key(self, key):
        with self._cv:
            self._data.pop(key, None)
        return True

    def num_keys(self):
        with self._cv:
            return len(self._data)

    def close(self):
        pass


class ResilientStore:
    """Outage-surviving control-plane store with epoch fencing.

    Owns the live ``_PyStoreServer`` master plus the promotion policy:

    * every op first probes the ``store.master_down`` fault site — a
      chaos plan firing ANY action there kills the live master
      in-place, exactly like a real master death;
    * a ``ConnectionError`` from the client is only treated as a dead
      master after a direct liveness probe of the master port fails
      (a transiently dropped op — injected or real — must NOT cost the
      master its data); then a standby ``_PyStoreServer`` is promoted:
      fresh empty server (the old master's memory is LOST by design —
      gossip digests republish on the next heartbeat, transport
      counters rewind, see ``StoreTransport.recv``), the **epoch** is
      bumped, and the client reconnects via the existing RetryPolicy;
    * writes (``set``/``add``/``delete_key``) accept ``lease=`` and are
      fenced with :class:`StoreEpochError` BEFORE touching the store
      when the lease's epoch is stale — a partitioned writer that
      missed a promotion can never double-own a request.

    Observability: ``store.epoch`` gauge, ``store.promotions`` /
    ``store.fenced_writes`` counters, ``store.promote_ms`` histogram,
    ``store.promoted`` / ``store.write_fenced`` instants."""

    def __init__(self, host="127.0.0.1", timeout=2.0, retries=0,
                 auto_promote=True):
        self._host = host
        self._timeout = float(timeout)
        self._retries = retries
        self.auto_promote = bool(auto_promote)
        self._lock = threading.RLock()
        self._epoch = 1
        self._lease_seq = 0
        self.promotions = 0
        self.fenced_writes = 0
        self._server = _PyStoreServer(0)
        self._client = self._new_client()
        obs.get_registry().gauge("store.epoch").set(self._epoch)

    # -- plumbing -----------------------------------------------------
    def _new_client(self):
        return TCPStore(self._host, self._server.port,
                        timeout=self._timeout, retries=self._retries)

    @property
    def port(self):
        """Port of the CURRENT master (changes across promotions)."""
        return self._server.port

    def epoch(self):
        return self._epoch

    def stats(self):
        return {"epoch": self._epoch, "promotions": self.promotions,
                "fenced_writes": self.fenced_writes}

    # -- leases / fencing ---------------------------------------------
    def acquire_lease(self, owner=None):
        """A fresh :class:`StoreLease` stamped with the current epoch."""
        with self._lock:
            self._lease_seq += 1
            name = owner if owner is not None \
                else f"lease{self._lease_seq}"
            return StoreLease(name, self._epoch)

    def renew(self, lease):
        """Re-stamp ``lease`` at the current epoch (a NEW lease).  Only
        a holder that can still REACH the store can renew — the fenced
        side of a partition cannot, which is the whole point."""
        return StoreLease(lease.owner, self._epoch)

    def _fence(self, lease, key):
        if lease is None:
            return
        if lease.epoch != self._epoch:
            self.fenced_writes += 1
            obs.get_registry().counter("store.fenced_writes").inc()
            obs.instant("store.write_fenced", cat="fault",
                        owner=lease.owner, lease_epoch=lease.epoch,
                        store_epoch=self._epoch)
            raise StoreEpochError(
                f"store write to {key!r} fenced: lease for "
                f"{lease.owner!r} carries epoch {lease.epoch} but the "
                f"store is at epoch {self._epoch} (a standby was "
                f"promoted; renew the lease before writing)",
                lease_epoch=lease.epoch, store_epoch=self._epoch,
                owner=lease.owner, key=key)

    # -- failure handling ---------------------------------------------
    def master_down(self):
        """Kill the live master in-place (what the ``store.master_down``
        fault site realizes): its listener and in-memory data die."""
        with self._lock:
            self._server.stop()

    def _master_alive(self):
        try:
            with socket.create_connection(
                    (self._host, self._server.port), timeout=0.25):
                return True
        except OSError:
            return False

    def promote_standby(self):
        """Promote the standby to master: fresh server, epoch+1,
        client reconnected.  Returns the new epoch."""
        with self._lock:
            t0 = time.perf_counter()
            try:
                self._client.close()
            except Exception:
                pass
            try:
                self._server.stop()
            except Exception:
                pass
            self._server = _PyStoreServer(0)
            self._epoch += 1
            self.promotions += 1
            self._client = self._new_client()
            ms = (time.perf_counter() - t0) * 1e3
            reg = obs.get_registry()
            reg.gauge("store.epoch").set(self._epoch)
            reg.counter("store.promotions").inc()
            reg.histogram("store.promote_ms").observe(ms)
            obs.instant("store.promoted", cat="fault",
                        epoch=self._epoch, promote_ms=round(ms, 3))
            return self._epoch

    def _call(self, fn):
        from .fault_tolerance.plan import InjectedFault
        try:
            fault_point("store.master_down")
        except InjectedFault:
            self.master_down()
        epoch0 = self._epoch
        try:
            return fn()
        except (ConnectionError, OSError) as e:
            if isinstance(e, TimeoutError) \
                    and not isinstance(e, ConnectionError):
                # a parked read running out its socket timeout is a
                # missing KEY, not a dead master
                raise
            if not self.auto_promote or self._master_alive():
                # transient op failure against a live master: surface
                # it (callers degrade / retry); promoting here would
                # cost the master its data for nothing
                raise
            with self._lock:
                if self._epoch == epoch0:
                    self.promote_standby()
            return fn()

    # -- the store API ------------------------------------------------
    def set(self, key, value, lease=None):
        self._fence(lease, key)
        return self._call(lambda: self._client.set(key, value))

    def get(self, key):
        return self._call(lambda: self._client.get(key))

    def query(self, key):
        return self._call(lambda: self._client.query(key))

    def add(self, key, amount=1, lease=None):
        self._fence(lease, key)
        return self._call(lambda: self._client.add(key, amount))

    def wait(self, keys, deadline=None):
        # StoreTimeoutError (NOT ConnectionError) surfaces from a dead
        # master here — the caller's deadline semantics stay exact; the
        # next non-wait op takes the promotion path
        return self._call(lambda: self._client.wait(keys,
                                                    deadline=deadline))

    def delete_key(self, key, lease=None):
        self._fence(lease, key)
        return self._call(lambda: self._client.delete_key(key))

    def num_keys(self):
        return self._call(lambda: self._client.num_keys())

    def close(self):
        try:
            self._client.close()
        except Exception:
            pass
        try:
            self._server.stop()
        except Exception:
            pass

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
