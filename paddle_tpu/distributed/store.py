"""TCPStore: the rendezvous key-value store.

Reference parity: `fluid/distributed/store/tcp_store.cc` +
`paddle.distributed.TCPStore` (master rank hosts the store; every rank
connects for set/get/add/wait/barrier during init_parallel_env
[UNVERIFIED — empty reference mount; SURVEY.md §2.1 "Comm runtime"]).

TPU-native split: ICI/DCN collectives never touch this store (XLA owns
them); what remains is host-side rendezvous — and that part is the
reference's design unchanged.  The SERVER is native C++
(`_native/tcp_store.cc`: thread-per-connection over a cv-guarded map,
blocking GET/WAIT park the caller server-side), built on first use; a
pure-python server is the fallback when no C++ toolchain exists.  The
client speaks the length-prefixed wire protocol over one socket.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

__all__ = ["TCPStore"]


class _PyStoreServer:
    """Python fallback server implementing the same wire protocol."""

    def __init__(self, port=0):
        self._data = {}
        self._cv = threading.Condition()
        self._stop = False
        self._srv = socket.create_server(("0.0.0.0", port))
        self.port = self._srv.getsockname()[1]
        self._threads = []
        t = threading.Thread(target=self._accept, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _read_n(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                cmd = self._read_n(conn, 1)
                if cmd == b"X":
                    self.stop()
                    return
                if cmd == b"N":
                    with self._cv:
                        n = len(self._data)
                    conn.sendall(struct.pack("<q", n))
                    continue
                (klen,) = struct.unpack("<I", self._read_n(conn, 4))
                key = self._read_n(conn, klen).decode()
                if cmd == b"S":
                    (vlen,) = struct.unpack("<Q", self._read_n(conn, 8))
                    val = self._read_n(conn, vlen) if vlen else b""
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif cmd in (b"G", b"W"):
                    with self._cv:
                        while key not in self._data and not self._stop:
                            self._cv.wait(0.1)
                        val = self._data.get(key, b"")
                    if cmd == b"W":
                        conn.sendall(b"\x01")
                    else:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"Q":
                    with self._cv:
                        has = key in self._data
                        val = self._data.get(key, b"")
                    conn.sendall(b"\x01" if has else b"\x00")
                    if has:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"A":
                    (amt,) = struct.unpack("<q", self._read_n(conn, 8))
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._data.get(
                                key, b"\0" * 8))[0] if len(
                            self._data.get(key, b"\0" * 8)) == 8 else 0
                        now = cur + amt
                        self._data[key] = struct.pack("<q", now)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", now))
                elif cmd == b"D":
                    with self._cv:
                        self._data.pop(key, None)
                    conn.sendall(b"\x01")
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._cv:
            self._cv.notify_all()


class TCPStore:
    """paddle.distributed.TCPStore-compatible client (+ server on the
    master rank).

    TCPStore(host, port, is_master=False, world_size=1, timeout=...)
    with set/get/add/wait/delete_key/num_keys/barrier.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=300, **kwargs):
        self._host = host
        self._world_size = world_size
        self._timeout = timeout
        self._server = None
        self._native_handle = None
        if is_master:
            from .._native import (tcp_store_available,
                                   start_tcp_store_server)
            if tcp_store_available():
                self._native_handle, port = \
                    start_tcp_store_server(port)
            else:
                self._server = _PyStoreServer(port)
                port = self._server.port
        self.port = port
        self._sock = None
        self._lock = threading.Lock()
        self._connect()

    # -- wire ------------------------------------------------------------
    def _connect(self):
        deadline = time.time() + self._timeout
        last = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection(
                    (self._host, self.port), timeout=self._timeout)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                return
            except OSError as e:
                last = e
                time.sleep(0.05)
        raise TimeoutError(
            f"TCPStore: cannot reach {self._host}:{self.port} ({last})")

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("TCPStore server closed")
            buf += chunk
        return buf

    def _req(self, cmd, key=None, payload=b""):
        msg = cmd
        if key is not None:
            kb = key.encode()
            msg += struct.pack("<I", len(kb)) + kb
        msg += payload
        self._sock.sendall(msg)

    # -- API -------------------------------------------------------------
    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            self._req(b"S", key,
                      struct.pack("<Q", len(value)) + bytes(value))
            self._read_n(1)

    def get(self, key):
        """Blocking get (waits until the key exists)."""
        with self._lock:
            self._req(b"G", key)
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""

    def query(self, key):
        """Non-blocking get: returns None when absent."""
        with self._lock:
            self._req(b"Q", key)
            has = self._read_n(1) == b"\x01"
            if not has:
                return None
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""

    def add(self, key, amount=1):
        with self._lock:
            self._req(b"A", key, struct.pack("<q", int(amount)))
            (now,) = struct.unpack("<q", self._read_n(8))
            return now

    def wait(self, keys):
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            with self._lock:
                self._req(b"W", k)
                self._read_n(1)

    def delete_key(self, key):
        with self._lock:
            self._req(b"D", key)
            self._read_n(1)
        return True

    def num_keys(self):
        with self._lock:
            self._req(b"N")
            (n,) = struct.unpack("<q", self._read_n(8))
            return n

    def barrier(self, tag="barrier"):
        """All world_size ranks block until everyone arrived."""
        n = self.add(f"__{tag}__", 1)
        round_ = (n - 1) // self._world_size
        target = (round_ + 1) * self._world_size
        deadline = time.time() + self._timeout
        while self.add(f"__{tag}__", 0) < target:
            if time.time() > deadline:
                raise TimeoutError(f"TCPStore barrier {tag!r} timed out")
            time.sleep(0.002)

    def close(self):
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        if self._native_handle is not None:
            from .._native import stop_tcp_store_server
            stop_tcp_store_server(self._native_handle)
            self._native_handle = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
