"""TCPStore: the rendezvous key-value store.

Reference parity: `fluid/distributed/store/tcp_store.cc` +
`paddle.distributed.TCPStore` (master rank hosts the store; every rank
connects for set/get/add/wait/barrier during init_parallel_env
[UNVERIFIED — empty reference mount; SURVEY.md §2.1 "Comm runtime"]).

TPU-native split: ICI/DCN collectives never touch this store (XLA owns
them); what remains is host-side rendezvous — and that part is the
reference's design unchanged.  The SERVER is native C++
(`_native/tcp_store.cc`: thread-per-connection over a cv-guarded map,
blocking GET/WAIT park the caller server-side), built on first use; a
pure-python server is the fallback when no C++ toolchain exists.  The
client speaks the length-prefixed wire protocol over one socket.

Hardening (fault_tolerance layer):
  * connect phase: exponential backoff with deterministic jitter — the
    master binding late (the startup race) no longer fails rank N hard
    on the first ECONNREFUSED;
  * per-op deadlines: the client socket carries ``timeout`` via
    ``settimeout``, so a dead server turns a blocking get into a named
    TimeoutError instead of an eternal hang;
  * bounded replay: idempotent ops (get/query/wait/num_keys) reconnect
    and retry up to ``PADDLE_TPU_STORE_RETRIES`` times on transient
    socket errors (a store restart mid-rendezvous is survivable);
  * ``fault_point("store.connect")`` / ``("store.<op>")`` sites let the
    FaultPlan drop or delay any of this deterministically.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

from .. import observability as obs
from .fault_tolerance.plan import fault_point
from .fault_tolerance.retry import (ENV_STORE_RETRIES,
                                    RetryExhausted, RetryPolicy)

__all__ = ["StoreTimeoutError", "TCPStore"]


class StoreTimeoutError(TimeoutError):
    """``TCPStore.wait`` ran out its hard deadline.

    Structured: ``keys`` is the full wait set, ``pending`` the keys
    not yet observed when the deadline hit, ``waited_s`` the wall time
    actually spent, ``deadline_s`` the budget.  Subclasses
    ``TimeoutError`` so pre-existing ``except TimeoutError`` callers
    keep working."""

    def __init__(self, msg, keys=(), pending=(), waited_s=0.0,
                 deadline_s=0.0):
        super().__init__(msg)
        self.keys = tuple(keys)
        self.pending = tuple(pending)
        self.waited_s = float(waited_s)
        self.deadline_s = float(deadline_s)


class _PyStoreServer:
    """Python fallback server implementing the same wire protocol."""

    def __init__(self, port=0):
        self._data = {}
        self._cv = threading.Condition()
        self._stop = False
        self._srv = socket.create_server(("0.0.0.0", port))
        self.port = self._srv.getsockname()[1]
        self._conns = set()
        self._conn_lock = threading.Lock()
        self._workers = []
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            if self._stop:  # woken by stop()'s self-connect
                conn.close()
                break
            with self._conn_lock:
                self._conns.add(conn)
                # reap finished workers so a long-lived server doesn't
                # accumulate dead Thread objects
                self._workers = [t for t in self._workers if t.is_alive()]
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._workers.append(t)

    def _read_n(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                cmd = self._read_n(conn, 1)
                if cmd == b"X":
                    self.stop()
                    return
                if cmd == b"N":
                    with self._cv:
                        n = len(self._data)
                    conn.sendall(struct.pack("<q", n))
                    continue
                (klen,) = struct.unpack("<I", self._read_n(conn, 4))
                key = self._read_n(conn, klen).decode()
                if cmd == b"S":
                    (vlen,) = struct.unpack("<Q", self._read_n(conn, 8))
                    val = self._read_n(conn, vlen) if vlen else b""
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif cmd in (b"G", b"W"):
                    with self._cv:
                        while key not in self._data and not self._stop:
                            self._cv.wait(0.1)
                        val = self._data.get(key, b"")
                    if self._stop and key not in self._data:
                        return
                    if cmd == b"W":
                        conn.sendall(b"\x01")
                    else:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"Q":
                    with self._cv:
                        has = key in self._data
                        val = self._data.get(key, b"")
                    conn.sendall(b"\x01" if has else b"\x00")
                    if has:
                        conn.sendall(struct.pack("<Q", len(val)) + val)
                elif cmd == b"A":
                    (amt,) = struct.unpack("<q", self._read_n(conn, 8))
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._data.get(
                                key, b"\0" * 8))[0] if len(
                            self._data.get(key, b"\0" * 8)) == 8 else 0
                        now = cur + amt
                        self._data[key] = struct.pack("<q", now)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", now))
                elif cmd == b"D":
                    with self._cv:
                        self._data.pop(key, None)
                    conn.sendall(b"\x01")
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conn_lock:
                self._conns.discard(conn)

    def stop(self):
        if self._stop:
            return
        self._stop = True
        try:
            # closing the listener does NOT interrupt a blocked accept()
            # on Linux — poke it awake so the accept thread can exit
            with socket.create_connection(("127.0.0.1", self.port),
                                          timeout=0.5):
                pass
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._cv:
            self._cv.notify_all()
        # closing live connections unblocks workers parked in recv()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=2)
        for t in self._workers:
            if t is not threading.current_thread():
                t.join(timeout=1)

    close = stop

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """paddle.distributed.TCPStore-compatible client (+ server on the
    master rank).

    TCPStore(host, port, is_master=False, world_size=1, timeout=...)
    with set/get/add/wait/delete_key/num_keys/barrier.  ``timeout``
    bounds the connect phase, every single op (via socket.settimeout),
    and barrier(); ``retries`` (default ``PADDLE_TPU_STORE_RETRIES``,
    3) bounds the replay of idempotent ops across reconnects.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=300, retries=None, **kwargs):
        self._host = host
        self._world_size = world_size
        self._timeout = float(timeout)
        self._retries = int(os.environ.get(ENV_STORE_RETRIES, "3")) \
            if retries is None else int(retries)
        self._server = None
        self._native_handle = None
        if is_master:
            from .._native import (tcp_store_available,
                                   start_tcp_store_server)
            if tcp_store_available():
                self._native_handle, port = \
                    start_tcp_store_server(port)
            else:
                self._server = _PyStoreServer(port)
                port = self._server.port
        self.port = port
        self._sock = None
        self._lock = threading.Lock()
        # per-call replay schedule for idempotent ops: a FRESH delay
        # sequence every call (a shared generator would saturate at
        # max_delay after the first few retries and stay there forever)
        self._op_policy = RetryPolicy(retries=self._retries, base=0.02,
                                      factor=2.0, max_delay=0.5)
        with self._lock:
            self._connect()

    # -- wire ------------------------------------------------------------
    def _connect(self):
        """Connect with exponential backoff + jitter until ``timeout``:
        the master rank binding late (startup race) is expected, not
        fatal."""

        def attempt():
            fault_point("store.connect")
            try:
                self._sock = socket.create_connection(
                    (self._host, self.port),
                    timeout=min(self._timeout, 5.0))
            except OSError:
                self._sock = None
                raise
            # per-op deadline: every later recv/send on this socket
            # fails with TimeoutError instead of hanging forever
            self._sock.settimeout(self._timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)

        policy = RetryPolicy(retries=None, base=0.05, factor=1.6,
                             max_delay=1.0)
        try:
            policy.call(attempt, exceptions=(OSError,),
                        deadline=time.monotonic() + self._timeout,
                        what="store.connect")
        except RetryExhausted as e:
            raise TimeoutError(
                f"TCPStore: cannot reach {self._host}:{self.port} "
                f"within {self._timeout}s (last error: {e.last})") \
                from e.last

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("TCPStore server closed")
            buf += chunk
        return buf

    def _req(self, cmd, key=None, payload=b""):
        msg = cmd
        if key is not None:
            kb = key.encode()
            msg += struct.pack("<I", len(kb)) + kb
        msg += payload
        self._sock.sendall(msg)

    def _call(self, op_name, fn, idempotent=False, deadline=None):
        """Run one wire op under the lock.  Transient socket errors
        drop the connection; idempotent ops reconnect and replay through
        ``RetryPolicy`` (the store may have restarted — get/wait/query
        replay safely; set/add/delete never do).  A reply *timeout* is
        never replayed: the stream is desynced, so the socket is
        poisoned and the error surfaces immediately."""

        class _ReplyTimeout(Exception):
            pass  # not an OSError: opts out of the replay policy

        def attempt():
            with self._lock:
                try:
                    if self._sock is None:
                        self._connect()
                    fault_point("store." + op_name)
                    return fn()
                except TimeoutError as e:
                    # reply stream is now desynced: poison the socket so
                    # the next op reconnects cleanly
                    self._drop_sock()
                    raise _ReplyTimeout() from e
                except (ConnectionError, OSError):
                    self._drop_sock()
                    raise

        try:
            if idempotent:
                return self._op_policy.call(
                    attempt, exceptions=(ConnectionError, OSError),
                    deadline=deadline, what="store." + op_name)
            return attempt()
        except _ReplyTimeout as e:
            raise TimeoutError(
                f"TCPStore {op_name!r}: no reply within "
                f"{self._timeout}s from "
                f"{self._host}:{self.port}") from e.__cause__
        except RetryExhausted as e:
            raise ConnectionError(
                f"TCPStore {op_name!r}: {self._retries + 1} attempt(s) "
                f"failed against {self._host}:{self.port} "
                f"(last error: {e.last})") from e.last

    # -- API -------------------------------------------------------------
    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        value = bytes(value)

        def fn():
            self._req(b"S", key, struct.pack("<Q", len(value)) + value)
            self._read_n(1)
        self._call("set", fn)

    def get(self, key):
        """Blocking get (waits until the key exists, up to timeout)."""
        def fn():
            self._req(b"G", key)
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""
        return self._call("get", fn, idempotent=True)

    def query(self, key):
        """Non-blocking get: returns None when absent."""
        def fn():
            self._req(b"Q", key)
            has = self._read_n(1) == b"\x01"
            if not has:
                return None
            (vlen,) = struct.unpack("<Q", self._read_n(8))
            return self._read_n(vlen) if vlen else b""
        return self._call("query", fn, idempotent=True)

    def add(self, key, amount=1):
        def fn():
            self._req(b"A", key, struct.pack("<q", int(amount)))
            (now,) = struct.unpack("<q", self._read_n(8))
            return now
        return self._call("add", fn)

    def wait(self, keys, deadline=None):
        """Block until every key exists — under a HARD deadline.

        ``deadline`` (seconds; default the store timeout) bounds the
        WHOLE wait: all keys, all reconnect retries (paced by the
        ``RetryPolicy``, which stops scheduling attempts past the
        deadline), and each server-side park (the socket timeout is
        shrunk to the remaining budget, so a wedged master cannot
        spin this past its bound).  On expiry raises
        :class:`StoreTimeoutError` naming the pending keys and emits
        a ``store.wait_timeout`` instant."""
        if isinstance(keys, str):
            keys = [keys]
        keys = list(keys)
        budget = self._timeout if deadline is None else float(deadline)
        t_end = time.monotonic() + budget

        def _expired(err, pending):
            waited = budget - max(0.0, t_end - time.monotonic())
            obs.instant("store.wait_timeout", cat="fault",
                        keys=len(keys), pending=pending[0],
                        waited_s=round(waited, 3),
                        deadline_s=round(budget, 3))
            raise StoreTimeoutError(
                f"TCPStore.wait: {len(pending)}/{len(keys)} key(s) "
                f"still absent after {waited:.3f}s "
                f"(deadline {budget:.3f}s); first pending: "
                f"{pending[0]!r}", keys=keys, pending=pending,
                waited_s=waited, deadline_s=budget) from err

        for n, k in enumerate(keys):
            def fn(k=k):
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"wait deadline expired before {k!r}")
                prev = self._sock.gettimeout()
                self._sock.settimeout(
                    min(prev, remaining) if prev else remaining)
                try:
                    self._req(b"W", k)
                    self._read_n(1)
                finally:
                    try:
                        self._sock.settimeout(prev)
                    except OSError:
                        pass
            try:
                self._call("wait", fn, idempotent=True, deadline=t_end)
            except (TimeoutError, ConnectionError) as e:
                _expired(e, keys[n:])

    def delete_key(self, key):
        def fn():
            self._req(b"D", key)
            self._read_n(1)
        self._call("delete_key", fn)
        return True

    def num_keys(self):
        def fn():
            self._req(b"N")
            (n,) = struct.unpack("<q", self._read_n(8))
            return n
        return self._call("num_keys", fn, idempotent=True)

    def barrier(self, tag="barrier"):
        """All world_size ranks block until everyone arrived."""
        n = self.add(f"__{tag}__", 1)
        round_ = (n - 1) // self._world_size
        target = (round_ + 1) * self._world_size
        deadline = time.monotonic() + self._timeout
        while self.add(f"__{tag}__", 0) < target:
            if time.monotonic() > deadline:
                raise TimeoutError(f"TCPStore barrier {tag!r} timed out")
            time.sleep(0.002)

    def close(self):
        with self._lock:
            self._drop_sock()
        if self._native_handle is not None:
            from .._native import stop_tcp_store_server
            stop_tcp_store_server(self._native_handle)
            self._native_handle = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
