from . import save_load
from .save_load import save_state_dict, load_state_dict
