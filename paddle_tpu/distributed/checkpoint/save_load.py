"""Distributed checkpoint: per-rank shards + metadata, reshard on load.

Reference parity: `python/paddle/distributed/checkpoint/save_state_dict.py`
/ `load_state_dict.py` (each rank saves owned shards + global metadata;
load reshards to the new topology) [UNVERIFIED — empty reference mount].

TPU-native: each host saves the addressable shards of its global arrays
with their index coordinates; load assembles the global value and
device_puts it under the *current* sharding — resharding across topologies
falls out (the Orbax-style flow, dependency-free).

Crash safety (fault_tolerance layer): every file is written atomically
(tmp + fsync + os.replace), the coordinator commits the checkpoint by
writing a sha256 ``manifest.json`` LAST, and load validates before
trusting — a worker killed mid-save leaves either the previous complete
checkpoint or a visibly-incomplete directory (no manifest), never a
silently-torn one.  ``load_state_dict(..., fallback_path=...)`` rolls
back to the last good generation on corruption.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np
import jax

from ...core.tensor import Tensor
from ..fault_tolerance.atomic import (atomic_write, write_manifest,
                                      validate_checkpoint,
                                      latest_good_checkpoint,
                                      CheckpointCorruptionError)
from ..fault_tolerance.plan import fault_point

__all__ = ["save_state_dict", "load_state_dict", "read_train_meta"]


def read_train_meta(path):
    """The ``"train"`` block (step / rng_key / data_cursor) a checkpoint
    manifest was committed with, or ``None`` for older checkpoints."""
    from ..fault_tolerance.atomic import MANIFEST_NAME
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f).get("train")
    except (OSError, ValueError):
        return None


def _proc_id():
    try:
        return jax.process_index()
    except Exception:
        return 0


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False,
                    train_meta=None):
    """``train_meta`` (optional dict, e.g. ``{"step": 12, "rng_key":
    [...], "data_cursor": 12}``) is committed into the manifest under a
    ``"train"`` key so a resume can restore step/RNG/data-loader
    position from the checkpoint alone."""
    os.makedirs(path, exist_ok=True)
    rank = _proc_id()
    shards = {}
    meta = {}
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            meta[name] = {"type": "object"}
            shards[name] = t
            continue
        arr = t._value
        meta[name] = {
            "type": "tensor",
            "global_shape": list(arr.shape),
            "dtype": t.dtype.name,
        }
        pieces = []
        seen_idx = set()
        try:
            for s in arr.addressable_shards:
                idx = [[sl.start or 0,
                        sl.stop if sl.stop is not None else dim]
                       for sl, dim in zip(s.index, arr.shape)]
                # under an SPMD mesh a replicated (or partially
                # replicated) array repeats the same shard on every
                # device of the replica axes — write each index once
                key = tuple(map(tuple, idx))
                if key in seen_idx:
                    continue
                seen_idx.add(key)
                pieces.append({"index": idx,
                               "data": np.asarray(s.data)})
        except Exception:
            pieces.append({"index": [[0, d] for d in arr.shape],
                           "data": np.asarray(arr)})
        shards[name] = pieces
    shard_path = os.path.join(path, f"shard_{rank}.pkl")
    with atomic_write(shard_path) as f:
        pickle.dump(shards, f)
    # FaultPlan site "checkpoint.write": a drop/kill here models a
    # worker dying MID-SAVE — the manifest never lands, so the
    # checkpoint is visibly incomplete (not silently torn)
    fault_point("checkpoint.write", path=shard_path)
    if rank == coordinator_rank:
        with atomic_write(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)
        # commit record, written LAST: a checkpoint without a manifest
        # is by definition incomplete
        write_manifest(path, extra={"train": dict(train_meta)}
                       if train_meta else None)
        # FaultPlan site "checkpoint.commit": a "corrupt" event here
        # mangles a committed file — post-commit bit-rot/torn replace,
        # exactly what the checksum manifest must catch at load time
        fault_point("checkpoint.commit", path=shard_path)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False, fallback_path=None, verify=True):
    """Fill `state_dict`'s tensors in place, resharding to their current
    placement.

    With ``verify`` (default), the checkpoint's manifest + checksums are
    validated first; a corrupt/incomplete checkpoint raises
    :class:`CheckpointCorruptionError` — or, when ``fallback_path`` is
    given (a sibling checkpoint or a directory of checkpoints), falls
    back to the newest valid generation instead.
    """
    if verify:
        ok, reasons = validate_checkpoint(path)
        if not ok:
            fb = None
            if fallback_path is not None:
                ok_fb, _ = validate_checkpoint(fallback_path)
                fb = fallback_path if ok_fb else \
                    latest_good_checkpoint(fallback_path)
            from ... import observability as obs
            if obs.enabled():
                obs.instant("ckpt.corrupt", cat="fault", path=str(path),
                            reasons="; ".join(reasons),
                            fallback=str(fallback_path or ""))
            if fb is None:
                raise CheckpointCorruptionError(path, reasons)
            import warnings
            warnings.warn(
                f"checkpoint {path!r} failed validation "
                f"({'; '.join(reasons)}); falling back to last good "
                f"checkpoint {fb!r}", RuntimeWarning, stacklevel=2)
            path = fb
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    all_shards = {}
    for fname in sorted(os.listdir(path)):
        if fname.startswith("shard_") and fname.endswith(".pkl"):
            with open(os.path.join(path, fname), "rb") as f:
                data = pickle.load(f)
            for name, pieces in data.items():
                all_shards.setdefault(name, []).extend(
                    pieces if isinstance(pieces, list) else [pieces])
    import jax.numpy as jnp

    for name, t in state_dict.items():
        if name not in meta:
            continue
        m = meta[name]
        if m["type"] != "tensor" or not isinstance(t, Tensor):
            continue
        full = np.zeros(m["global_shape"],
                        np.float32 if m["dtype"] == "bfloat16"
                        else np.dtype(m["dtype"]))
        for piece in all_shards.get(name, []):
            idx = tuple(slice(a, b) for a, b in piece["index"])
            full[idx] = piece["data"]
        val = jnp.asarray(full, t._value.dtype)
        # reshard to the current placement: the tensor's live sharding
        # if it has been placed, else the active MeshPlan's rule for it
        # (loading a fresh model under a NEW mesh topology lands each
        # param pre-sharded instead of replicated)
        sh = getattr(t._value, "sharding", None)
        if sh is None or getattr(sh, "is_fully_replicated", True):
            from ..auto_parallel import sharding as spmd
            plan = spmd.get_mesh_plan()
            if plan is not None and not plan.is_virtual:
                sh = plan.sharding(plan.spec_for(
                    spmd.spmd_name(t), tuple(val.shape)))
        try:
            val = jax.device_put(val, sh) if sh is not None else val
        except Exception:
            pass
        t._inplace_update(val)
    return state_dict
