"""Distributed checkpoint: per-rank shards + metadata, reshard on load.

Reference parity: `python/paddle/distributed/checkpoint/save_state_dict.py`
/ `load_state_dict.py` (each rank saves owned shards + global metadata;
load reshards to the new topology) [UNVERIFIED — empty reference mount].

TPU-native: each host saves the addressable shards of its global arrays
with their index coordinates; load assembles the global value and
device_puts it under the *current* sharding — resharding across topologies
falls out (the Orbax-style flow, dependency-free).
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np
import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _proc_id():
    try:
        return jax.process_index()
    except Exception:
        return 0


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    rank = _proc_id()
    shards = {}
    meta = {}
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            meta[name] = {"type": "object"}
            shards[name] = t
            continue
        arr = t._value
        meta[name] = {
            "type": "tensor",
            "global_shape": list(arr.shape),
            "dtype": t.dtype.name,
        }
        pieces = []
        try:
            for s in arr.addressable_shards:
                idx = [[sl.start or 0,
                        sl.stop if sl.stop is not None else dim]
                       for sl, dim in zip(s.index, arr.shape)]
                pieces.append({"index": idx,
                               "data": np.asarray(s.data)})
        except Exception:
            pieces.append({"index": [[0, d] for d in arr.shape],
                           "data": np.asarray(arr)})
        shards[name] = pieces
    with open(os.path.join(path, f"shard_{rank}.pkl"), "wb") as f:
        pickle.dump(shards, f)
    if rank == coordinator_rank:
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in place, resharding to their current
    placement."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    all_shards = {}
    for fname in sorted(os.listdir(path)):
        if fname.startswith("shard_") and fname.endswith(".pkl"):
            with open(os.path.join(path, fname), "rb") as f:
                data = pickle.load(f)
            for name, pieces in data.items():
                all_shards.setdefault(name, []).extend(
                    pieces if isinstance(pieces, list) else [pieces])
    import jax.numpy as jnp

    for name, t in state_dict.items():
        if name not in meta:
            continue
        m = meta[name]
        if m["type"] != "tensor" or not isinstance(t, Tensor):
            continue
        full = np.zeros(m["global_shape"],
                        np.float32 if m["dtype"] == "bfloat16"
                        else np.dtype(m["dtype"]))
        for piece in all_shards.get(name, []):
            idx = tuple(slice(a, b) for a, b in piece["index"])
            full[idx] = piece["data"]
        val = jnp.asarray(full, t._value.dtype)
        try:
            val = jax.device_put(val, t._value.sharding)
        except Exception:
            pass
        t._inplace_update(val)
    return state_dict
