"""Distributed environment: rank/world accessors + multi-controller init.

Reference parity: env parsing in `python/paddle/distributed/collective.py`
(`init_parallel_env`: PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS,
TCPStore rendezvous, ProcessGroupNCCL default group) [UNVERIFIED — empty
reference mount].

TPU-native: there is one JAX process per host (multi-controller); global
device count = world size in chips.  ``init_parallel_env`` performs
``jax.distributed.initialize`` when multi-host env vars are present, then
builds the global device Mesh.  PADDLE_* env vars are honored for
launcher compatibility.
"""
from __future__ import annotations

import os

import numpy as np
import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "parallel_device_count", "global_mesh",
           "set_global_mesh", "ParallelEnv", "device_mesh_shape"]

_initialized = False
_global_mesh = None


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank()
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    """Device-level SPMD world size (one rank per chip).

    NB: deliberately NOT PADDLE_TRAINERS_NUM — that env var counts
    controller PROCESSES (one per host, set by the launch CLI) and only
    feeds jax.distributed.initialize; the mesh/topology world is the
    global chip count, which jax.device_count() reports across all
    processes once the runtime is initialized."""
    if group is not None:
        return group.nranks
    return jax.device_count()


def parallel_device_count():
    return jax.local_device_count()


def init_parallel_env(strategy=None):
    """Initialize the distributed runtime.

    Multi-host: uses jax.distributed coordination (reference: TCPStore +
    nccl comm init).  Single-host: builds the mesh over local devices.
    """
    global _initialized, _global_mesh
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("MASTER_ADDR")
    # total controller processes: set by the launch CLI
    # (= nnodes * nproc_per_node); one per host on TPU
    nprocs = int(os.environ.get(
        "PADDLE_TRAINERS_NUM", os.environ.get("PADDLE_NNODES", "1")))
    from .jax_compat import distributed_initialized
    if nprocs > 1 and coord and not distributed_initialized():
        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord.split(':')[0]}:{port}",
            num_processes=nprocs,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    _initialized = True
    if _global_mesh is None:
        devs = np.array(jax.devices())
        _global_mesh = jax.sharding.Mesh(devs, ("dp",))
    return ParallelEnv()


def global_mesh():
    """The framework-wide device mesh (created lazily).

    An active :class:`~.auto_parallel.sharding.MeshPlan`
    (``PADDLE_TPU_MESH`` or ``set_mesh_plan``) defines the topology;
    otherwise every visible device forms a 1-D ``dp`` mesh."""
    global _global_mesh
    if _global_mesh is None:
        from .auto_parallel.sharding import get_mesh_plan
        plan = get_mesh_plan()
        if plan is not None and not plan.is_virtual:
            _global_mesh = plan.mesh
        else:
            devs = np.array(jax.devices())
            _global_mesh = jax.sharding.Mesh(devs, ("dp",))
    return _global_mesh


def set_global_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh


def device_mesh_shape():
    m = global_mesh()
    return dict(zip(m.axis_names, m.devices.shape))


class ParallelEnv:
    """Reference parity: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
