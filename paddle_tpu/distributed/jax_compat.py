"""Bridges over jax APIs that moved between releases.

Newer jax spells these ``jax.shard_map`` (with ``check_vma=``) and
``jax.lax.axis_size``; the pinned 0.4.37 has
``jax.experimental.shard_map.shard_map`` (with ``check_rep=``) and
``jax.core.axis_frame(name)`` returning the size directly.  Call sites
that need to run on either go through this module.
"""
import inspect

import jax

__all__ = ["shard_map", "axis_size", "distributed_initialized"]

_CHECK_KWARG = None  # resolved once per process


def shard_map(fn, *, mesh, in_specs, out_specs, check=False):
    """shard_map with the replication check spelled per installed jax
    (``check_vma`` vs ``check_rep``)."""
    global _CHECK_KWARG
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    if _CHECK_KWARG is None:
        params = inspect.signature(sm).parameters
        _CHECK_KWARG = next(
            (k for k in ("check_vma", "check_rep") if k in params), "")
    kw = {_CHECK_KWARG: check} if _CHECK_KWARG else {}
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis_name):
    """Static size of a named mesh axis, callable inside a traced
    shard_map/pmap body (the result is a Python int, so it can drive
    e.g. ppermute permutation lists)."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def distributed_initialized():
    """``jax.distributed.is_initialized()`` where it exists, else the
    coordination client's presence in the runtime global state."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        return False
