"""incubate.distributed: MoE models (expert parallelism)."""
from . import models
