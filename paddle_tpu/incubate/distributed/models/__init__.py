from . import moe
