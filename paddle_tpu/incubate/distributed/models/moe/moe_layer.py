"""MoE layer with capacity-based expert dispatch.

Reference parity: `python/paddle/incubate/distributed/models/moe/
moe_layer.py` using global_scatter/global_gather all-to-all [UNVERIFIED —
empty reference mount].

TPU-native: dispatch/combine are einsums against a one-hot
(token→expert,slot) tensor — the standard XLA MoE formulation (GShard).
Under expert parallelism the expert dimension is sharded on the 'ep' mesh
axis and XLA inserts the all-to-alls that `global_scatter/global_gather`
perform explicitly in the reference (see
distributed/fleet/meta_parallel/expert_parallel.py for the shard_map form).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.dispatch import dispatch
from .....nn import Layer, LayerList
from .gate import NaiveGate, GShardGate, SwitchGate

__all__ = ["MoELayer"]


def _dispatch_combine(x, gate_probs, topk_idx, topk_val, capacity):
    """Build dispatch/combine one-hots and run a dense capacity routing.

    x: [N, D]; returns (dispatched [E, C, D], combine [N, E, C])."""
    N, D = x.shape
    E = gate_probs.shape[-1]
    k = topk_idx.shape[-1]
    C = capacity
    locations = []
    # position of each token within its expert (per k-choice)
    prio = jnp.zeros((N, E), jnp.int32)
    combine = jnp.zeros((N, E, C), x.dtype)
    disp = jnp.zeros((N, E, C), jnp.bool_)
    counts = jnp.zeros((E,), jnp.int32)
    for j in range(k):
        idx = topk_idx[:, j]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot + counts[None]
        counts = counts + jnp.sum(onehot, axis=0)
        loc = jnp.sum(pos_in_expert * onehot, axis=-1)  # [N]
        keep = loc < C
        w = topk_val[:, j] * keep.astype(x.dtype)
        oh_c = jax.nn.one_hot(jnp.where(keep, loc, C), C + 1,
                              dtype=x.dtype)[:, :C]
        contrib = w[:, None, None] * onehot.astype(x.dtype)[:, :, None] * \
            oh_c[:, None, :]
        combine = combine + contrib
        disp = disp | (contrib > 0)
    dispatched = jnp.einsum("nec,nd->ecd", disp.astype(x.dtype), x)
    return dispatched, combine


class MoELayer(Layer):
    """moe = MoELayer(d_model, experts=LayerList([...]), gate=...)."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, capacity_factor=1.2,
                 top_k=2, **kwargs):
        super().__init__()
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) else \
            LayerList(experts or [])
        num_expert = len(self.experts)
        if gate is None or isinstance(gate, str):
            gate_type = gate or "gshard"
            if gate_type == "switch":
                self.gate = SwitchGate(d_model, num_expert)
                top_k = 1
            elif gate_type == "naive":
                self.gate = NaiveGate(d_model, num_expert, topk=top_k)
            else:
                self.gate = GShardGate(d_model, num_expert, topk=top_k)
        elif isinstance(gate, dict):
            self.gate = GShardGate(d_model, num_expert,
                                   topk=gate.get("top_k", top_k))
        else:
            self.gate = gate
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss = None
        self._ep_engine = None  # ExpertParallelEngine | False
        self._ep_mesh = None    # mesh the cached decision was made for

    def _maybe_ep_engine(self):
        """Build the ep-axis SPMD engine lazily when the global mesh has
        an expert axis (see meta_parallel/expert_parallel.py).  The
        decision is re-evaluated whenever the global mesh changes, so a
        warm-up forward before fleet.init() doesn't disable EP forever."""
        from .....distributed.env import global_mesh
        mesh = global_mesh()
        if self._ep_engine is not None and mesh is self._ep_mesh:
            return self._ep_engine or None
        self._ep_mesh = mesh
        axis = None
        if mesh is not None:
            for cand in ("ep", "expert"):
                if cand in mesh.axis_names and mesh.shape[cand] > 1:
                    axis = cand
                    break
        if axis is None:
            self._ep_engine = False
        else:
            try:
                from .....distributed.fleet.meta_parallel.\
                    expert_parallel import ExpertParallelEngine
                self._ep_engine = ExpertParallelEngine(
                    self, mesh=mesh, axis=axis)
            except Exception as e:
                import logging
                logging.getLogger("paddle_tpu.moe").warning(
                    "MoE: '%s' mesh axis present but expert parallelism "
                    "unavailable (%s); running the dense replicated "
                    "path", axis, e)
                self._ep_engine = False
        return self._ep_engine or None

    def forward(self, x):
        from .....ops.manipulation import reshape

        orig_shape = list(x.shape)
        N = 1
        for s in orig_shape[:-1]:
            N *= s
        d = orig_shape[-1]
        xf = reshape(x, [N, d])

        engine = self._maybe_ep_engine()
        if engine is not None:
            import numpy as _np
            n_shards = int(_np.prod(
                [engine.mesh.shape[a] for a in engine.tok_axes]))
            E = len(self.experts)
            if N % n_shards == 0:
                C = max(int(self.capacity_factor * (N // n_shards) *
                            self.top_k / max(E, 1)), 1)
                ne = len(engine.expert_tensors)

                def impl(xv, *pv, C):
                    return engine(xv, pv[:ne], pv[ne:], C)

                y, aux = dispatch(
                    "moe_ep", impl,
                    (xf,) + tuple(engine.expert_tensors)
                    + tuple(engine.gate_tensors), dict(C=C))
                self.aux_loss = aux
                return reshape(y, orig_shape)

        probs, topk_idx, topk_val, aux = self.gate(xf)
        self.aux_loss = aux
        E = len(self.experts)
        C = max(int(self.capacity_factor * N * self.top_k / max(E, 1)), 1)

        def route(xv, pv, iv, vv, *, C):
            return _dispatch_combine(xv, pv, iv, vv, C)

        dispatched, combine = dispatch(
            "moe_dispatch", route, (xf, probs, topk_idx, topk_val),
            dict(C=C))
        # expert FFNs on [E, C, D] — one slice per expert
        from .....ops.manipulation import unbind, stack
        expert_ins = unbind(dispatched, 0)
        expert_outs = [exp(t) for exp, t in zip(self.experts, expert_ins)]
        eout = stack(expert_outs, 0)  # [E, C, D]

        def comb(ev, cv):
            return jnp.einsum("nec,ecd->nd", cv, ev)

        out = dispatch("moe_combine", comb, (eout, combine), {})
        return reshape(out, orig_shape)
