"""MoE-aware global-norm clip.

Reference parity: `python/paddle/incubate/distributed/models/moe/
grad_clip.py` — expert params' grad norms are reduced over the moe group
so the global norm matches the unsharded model [UNVERIFIED — empty
reference mount].
"""
from __future__ import annotations

import jax.numpy as jnp

from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group

    def __call__(self, params):
        # single-controller SPMD: expert grads already live on the global
        # mesh; the plain global norm is correct.  (Multi-controller EP
        # would psum expert norms over the moe_group axis here.)
        return super().__call__(params)
