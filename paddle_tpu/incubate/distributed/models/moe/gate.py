"""MoE gates: naive top-k, GShard, Switch.

Reference parity: `python/paddle/incubate/distributed/models/moe/gate/`
[UNVERIFIED — empty reference mount].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.dispatch import dispatch
from .....nn import Layer, Linear

__all__ = ["NaiveGate", "GShardGate", "SwitchGate"]


class NaiveGate(Layer):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.gate = Linear(d_model, num_expert * world_size)
        self.top_k = topk
        self.num_expert = num_expert * world_size

    def forward(self, x):
        """Returns (gate_scores [N, E], topk_idx [N, k], topk_val [N, k],
        aux_loss)."""
        logits = self.gate(x)
        k = self.top_k

        def impl(lg, *, k):
            probs = jax.nn.softmax(lg.astype(jnp.float32), -1)
            val, idx = jax.lax.top_k(probs, k)
            val = val / jnp.sum(val, -1, keepdims=True)
            # load-balancing aux loss (GShard eq.): E * mean(f_e * P_e)
            E = lg.shape[-1]
            me = jnp.mean(probs, axis=0)
            onehot = jax.nn.one_hot(idx[:, 0], E, dtype=probs.dtype)
            ce = jnp.mean(onehot, axis=0)
            aux = E * jnp.sum(me * ce)
            return probs.astype(lg.dtype), idx.astype(jnp.int64), \
                val.astype(lg.dtype), aux.astype(lg.dtype)

        return dispatch("moe_gate", impl, (logits,), dict(k=k))


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
