"""paddle.incubate parity: fused functional ops + MoE entry points.

Reference parity: `python/paddle/incubate/` (`nn/functional/fused_*`,
`distributed/models/moe/`) [UNVERIFIED — empty reference mount].  On TPU
the "fused" ops are the same XLA-fused compositions (plus Pallas for the
hot ones) — exposed under the incubate names for API parity.
"""
from . import nn
from . import distributed  # MoE lives here (incubate.distributed.models.moe)
from . import autograd  # vjp/jvp/Jacobian/Hessian transforms
from . import optimizer  # LookAhead / ModelAverage


def autograd_functional_jacobian(func, xs):
    """Dense Jacobian of func at xs (incubate.autograd parity) via
    reverse-mode jax.jacrev over the framework's pure-op core."""
    import jax
    from ..core.tensor import Tensor
    from ..core.autograd import no_grad

    single = isinstance(xs, Tensor)
    xs_t = [xs] if single else list(xs)
    vals = [x._value for x in xs_t]

    def pure(*vs):
        with no_grad():
            out = func(*[Tensor(v, _internal=True, stop_gradient=True)
                         for v in vs])
        return out._value if isinstance(out, Tensor) else out

    jac = jax.jacrev(pure, argnums=tuple(range(len(vals))))(*vals)
    wrapped = tuple(Tensor(j, _internal=True, stop_gradient=True)
                    for j in jac)
    return wrapped[0] if single else wrapped

# lazy eager mode (SURVEY.md §7 "dygraph without per-op sync")
from ..core.lazy import (lazy_guard as lazy_eager,  # noqa: F401
                         enable_lazy, flush as lazy_flush)
