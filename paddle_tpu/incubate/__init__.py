"""paddle.incubate parity: fused functional ops + MoE entry points.

Reference parity: `python/paddle/incubate/` (`nn/functional/fused_*`,
`distributed/models/moe/`) [UNVERIFIED — empty reference mount].  On TPU
the "fused" ops are the same XLA-fused compositions (plus Pallas for the
hot ones) — exposed under the incubate names for API parity.
"""
from . import nn
from . import distributed  # MoE lives here (incubate.distributed.models.moe)


def autograd_functional_jacobian(func, xs):
    raise NotImplementedError
