"""paddle.incubate.autograd: functional transforms (vjp/jvp/Jacobian/
Hessian/forward_grad).

Reference parity: `python/paddle/incubate/autograd/` [UNVERIFIED —
empty reference mount].  TPU-native: these are direct exposures of
jax's transform set over the framework's pure-op core — the reference
builds them from double-grad op rules; here jax.jacrev/jacfwd/jvp/vjp
compose for free because every op bottoms out in traceable JAX.
"""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "jacobian", "hessian",
           "forward_grad"]


def _pure(func, n_in):
    import jax
    from ..core.autograd import no_grad

    def fn(*vals):
        with no_grad():
            out = func(*[Tensor(v, _internal=True, stop_gradient=True)
                         for v in vals])
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return fn


def _vals(xs):
    single = isinstance(xs, Tensor)
    lst = [xs] if single else list(xs)
    return single, [x._value for x in lst]


def _wrap(v):
    if isinstance(v, (tuple, list)):
        return tuple(_wrap(x) for x in v)
    return Tensor(v, _internal=True, stop_gradient=True)


def vjp(func, xs, v=None):
    """(outputs, vjp_result): reverse-mode products (cotangent v)."""
    import jax
    single, vals = _vals(xs)
    out, pullback = jax.vjp(_pure(func, len(vals)), *vals)
    if v is None:
        import jax.numpy as jnp
        cot = jax.tree.map(jnp.ones_like, out)
    else:
        cot = v._value if isinstance(v, Tensor) else tuple(
            x._value for x in v)
    grads = pullback(cot)
    g = grads[0] if single else grads
    return _wrap(out), _wrap(g)


def jvp(func, xs, v=None):
    """(outputs, jvp_result): forward-mode products (tangent v)."""
    import jax
    import jax.numpy as jnp
    single, vals = _vals(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vs = [v] if isinstance(v, Tensor) else list(v)
        tangents = tuple(x._value for x in vs)
    out, tangent_out = jax.jvp(_pure(func, len(vals)), tuple(vals),
                               tangents)
    return _wrap(out), _wrap(tangent_out)


class Jacobian:
    """Lazy dense Jacobian: J[:] materializes, J[i, j] slices.

    Multi-input functions concatenate the per-input Jacobian blocks
    along the flattened input axis (reference semantics: one matrix of
    shape [num_outputs, total_num_inputs])."""

    def __init__(self, func, xs, is_batched=False):
        import jax
        import numpy as np
        single, vals = _vals(xs)
        jac = jax.jacrev(_pure(func, len(vals)),
                         argnums=tuple(range(len(vals))))(*vals)
        self._single = single
        self._jac = jac[0] if single else jac
        self._in_sizes = [int(max(1, np.prod(v.shape))) for v in vals]
        self.is_batched = is_batched

    def _matrix(self):
        import numpy as np
        blocks = [self._jac] if self._single else list(self._jac)
        # each jacrev block has shape out_shape + in_shape_i; the input
        # element count is known, so n_out = size // n_in regardless of
        # the output rank → flatten to [n_out, n_in_i] and concatenate
        # the input axis ([num_outputs, total_num_inputs], reference
        # shape)
        mats = []
        for a, n_in in zip(blocks, self._in_sizes):
            a = np.asarray(a)
            n_out = max(1, a.size // n_in)
            mats.append(a.reshape(n_out, n_in))
        return mats[0] if len(mats) == 1 else np.concatenate(mats,
                                                             axis=-1)

    def __getitem__(self, idx):
        return _wrap(self._matrix()[idx])

    def numpy(self):
        return self._matrix()


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        import jax
        single, vals = _vals(xs)
        hess = jax.hessian(_pure(func, len(vals)))(*vals)
        self._h = hess
        self.is_batched = is_batched

    def __getitem__(self, idx):
        import numpy as np
        h = np.asarray(self._h)
        n = int(np.sqrt(h.size)) if h.ndim != 2 else h.shape[0]
        return _wrap(h.reshape(n, -1)[idx])

    def numpy(self):
        import numpy as np
        return np.asarray(self._h)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    j = Jacobian(func, xs)
    return _wrap(j.numpy())


def hessian(func, xs, create_graph=False, allow_unused=False):
    h = Hessian(func, xs)
    return _wrap(h.numpy())


def forward_grad(func, xs, v=None):
    """Forward-mode gradient (the reference's primal-transpose path)."""
    return jvp(func, xs, v)[1]
