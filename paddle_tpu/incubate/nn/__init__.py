"""paddle.incubate.nn: fused op functional parity."""
from . import functional
from .functional import (fused_linear, fused_feedforward,
                         fused_multi_head_attention, fused_rms_norm,
                         fused_layer_norm, fused_rotary_position_embedding,
                         fused_bias_act, swiglu, top_p_sampling)
