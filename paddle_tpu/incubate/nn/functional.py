"""Fused functional ops (incubate.nn.functional parity).

Reference parity: phi `fusion/` kernels — fused_attention, fused_rope,
fused_bias_act, fused_rms_norm [UNVERIFIED — empty reference mount].
TPU-native: each is ONE dispatch so the whole composite is a single XLA
fusion (and a Pallas kernel where it matters: rms_norm/attention — see
ops/pallas_kernels.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch
from ...core.tensor import Tensor

__all__ = ["fused_linear", "fused_feedforward", "fused_multi_head_attention",
           "fused_rms_norm", "fused_layer_norm",
           "fused_rotary_position_embedding", "fused_bias_act", "swiglu",
           "fused_dropout_add", "fused_linear_activation",
           "top_p_sampling"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def impl(v, w, *b, tw):
        if tw:
            w = w.T
        out = v @ w
        if b:
            out = out + b[0]
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch("fused_gemm_epilogue", impl, args,
                    dict(tw=bool(transpose_weight)))


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    def impl(v, w, b, *, tx, ty, act):
        if tx:
            v = v.T
        if ty:
            w = w.T
        out = v @ w + b
        if act == "gelu":
            return jax.nn.gelu(out)
        if act == "relu":
            return jnp.maximum(out, 0)
        return out

    return dispatch("fused_linear_activation", impl, (x, y, bias),
                    dict(tx=bool(trans_x), ty=bool(trans_y),
                         act=activation))


def swiglu(x, y=None, name=None):
    if y is not None:
        return dispatch("swiglu", lambda a, b: jax.nn.silu(a) * b, (x, y),
                        {})

    def impl(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b

    return dispatch("swiglu", impl, (x,), {})


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", **kwargs):
    def impl(v, *b, act):
        out = v + b[0] if b else v
        if act == "gelu":
            return jax.nn.gelu(out)
        if act in ("swiglu", "silu"):
            return jax.nn.silu(out)
        if act == "relu":
            return jnp.maximum(out, 0)
        return out

    args = (x,) + ((bias,) if bias is not None else ())
    return dispatch("fused_bias_act", impl, args, dict(act=act_method))


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    from ...nn.functional.norm import rms_norm

    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        from ...ops.math import add
        out = add(out, norm_bias)
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    from ...nn.functional.norm import layer_norm

    shape = tuple(x.shape[begin_norm_axis:]) if begin_norm_axis != -1 else \
        (x.shape[-1],)
    return layer_norm(x, list(shape), norm_weight, norm_bias, epsilon), None


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ...nn.functional.common import dropout
    from ...ops.math import add

    return add(dropout(x, p, training=training, mode=mode), y)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE applied to q/k ([B, S, H, D] layout)."""

    def make_sincos(seq, dim, dtype, base):
        inv = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) /
                              dim))
        t = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        return jnp.sin(freqs).astype(dtype), jnp.cos(freqs).astype(dtype)

    def rotate(v, s, c, neox):
        """s/c already broadcastable to [B-or-1, S, 1, D/2]."""
        D = v.shape[-1]
        if neox:
            v1, v2 = v[..., :D // 2], v[..., D // 2:]
            return jnp.concatenate([v1 * c - v2 * s, v2 * c + v1 * s], -1)
        v1, v2 = v[..., 0::2], v[..., 1::2]
        out = jnp.stack([v1 * c - v2 * s, v2 * c + v1 * s], axis=-1)
        return out.reshape(v.shape)

    def rope(v, sin_, cos_, neox):  # [S, D/2] tables
        return rotate(v, sin_[None, :, None, :], cos_[None, :, None, :],
                      neox)

    if time_major:
        raise NotImplementedError(
            "fused_rotary_position_embedding: time_major layout is not "
            "supported (use [B, S, H, D])")

    def impl(qv, *rest, has_k, has_v, has_sc, has_pos, neox, base):
        i = 0
        kv = rest[i] if has_k else None
        i += 1 if has_k else 0
        vv = rest[i] if has_v else None
        i += 1 if has_v else 0
        S, D = qv.shape[1], qv.shape[-1]
        if has_sc:
            # user-supplied tables: accept [S, D/2] or paddle's
            # [1, S, 1, D/2] (squeeze the broadcast dims)
            sin_, cos_ = rest[i], rest[i + 1]
            i += 2
            sin_ = sin_.reshape(sin_.shape[-3], sin_.shape[-1]) \
                if sin_.ndim == 4 else sin_
            cos_ = cos_.reshape(cos_.shape[-3], cos_.shape[-1]) \
                if cos_.ndim == 4 else cos_
            sin_ = sin_.astype(qv.dtype)
            cos_ = cos_.astype(qv.dtype)
        else:
            sin_, cos_ = make_sincos(S, D, qv.dtype, base)
        if has_pos:
            pos = rest[i]
            if has_sc:
                # user table: clamp (table assumed to cover positions;
                # jnp.take's default fill mode would emit NaN)
                sin_p = jnp.take(sin_, pos, axis=0, mode="clip")
                cos_p = jnp.take(cos_, pos, axis=0, mode="clip")
            else:
                # no table: compute the angle directly from the
                # position — exact for ANY id (KV-cache decode reaches
                # positions >= this call's seq_len)
                inv = 1.0 / (base ** (
                    jnp.arange(0, D, 2, dtype=jnp.float32) / D))
                fr = pos.astype(jnp.float32)[..., None] * inv
                sin_p = jnp.sin(fr).astype(qv.dtype)
                cos_p = jnp.cos(fr).astype(qv.dtype)

            def apply(v, s_, c_, nx, _sp=sin_p, _cp=cos_p):
                del s_, c_
                return rotate(v, _sp[:, :, None, :], _cp[:, :, None, :],
                              nx)
        else:
            apply = rope
        outs = [apply(qv, sin_, cos_, neox)]
        if kv is not None:
            outs.append(apply(kv, sin_, cos_, neox))
        if vv is not None:
            outs.append(vv)
        return tuple(outs) if len(outs) > 1 else outs[0]

    has_sc = sin is not None and cos is not None
    args = (q,) + tuple(t for t in (k, v) if t is not None)
    if has_sc:
        args += (sin, cos)
    if position_ids is not None:
        args += (position_ids,)
    out = dispatch("fused_rope", impl, args,
                   dict(has_k=k is not None, has_v=v is not None,
                        has_sc=has_sc,
                        has_pos=position_ids is not None,
                        neox=bool(use_neox_rotary_style),
                        base=float(rotary_emb_base)))
    if isinstance(out, tuple):
        res = list(out)
        while len(res) < 3:
            res.append(None)
        return tuple(res)
    return out, None, None


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      name=None):
    from ...nn import functional as F
    from ...ops.math import add

    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = F.gelu(h) if activation == "gelu" else F.relu(h)
    h = F.dropout(h, dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training)
    out = add(residual, h)
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode=None,
                               num_heads=None, **kwargs):
    from ...nn import functional as F
    from ...ops.math import add
    from ...ops.manipulation import reshape, transpose

    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    B, S, E = x.shape
    # qkv_weight: [3, num_heads, head_dim, E]
    nh = qkv_weight.shape[1]
    hd = qkv_weight.shape[2]
    from ...ops.linalg import einsum
    qkv = einsum("bse,thde->bsthd", x, qkv_weight)
    if qkv_bias is not None:
        qkv = add(qkv, qkv_bias)
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0)
    out = reshape(out, [B, S, nh * hd])
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training)
    out = add(residual, out)
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def _nucleus_mask(probs, top_p):
    """Keep-mask of each row's smallest prefix of descending-probability
    tokens whose cumulative mass reaches ``top_p[row]`` (rows with
    ``top_p >= 1`` keep everything).  Boundary rule: a token stays while
    the cumulative mass *before* it is < top_p — matching
    models/generation._sample_logits and the serving engine's in-graph
    sampler, which imports this helper."""
    order = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    keep_sorted = (cum - sorted_p) < top_p[:, None]
    rows = jnp.arange(probs.shape[0])[:, None]
    keep = jnp.zeros(probs.shape, bool).at[rows, order].set(keep_sorted)
    return keep | (top_p[:, None] >= 1.0)


def top_p_sampling(x, ps, threshold=None, seed=-1, name=None):
    """Nucleus (top-p) sampling over a batch of probability rows.

    x: [B, V] probabilities (renormalized internally); ps: [B] or [B, 1]
    per-row nucleus thresholds.  ``threshold`` additionally drops
    candidates whose filtered probability falls below it.  ``seed >= 0``
    draws with a fixed PRNG key — repeated calls with the same inputs
    and seed return identical tokens; ``seed == -1`` (default) threads
    the global generator like ``paddle.multinomial``.

    Returns ``(next_scores [B, 1], next_ids [B, 1] int64)`` where the
    score is the (pre-filter, renormalized) probability of the chosen
    token.
    """
    thr = None if threshold is None else float(threshold)

    def impl(key, probs, p_row, *, thr, stateful):
        if stateful:
            new, sub = jax.random.split(key)
        else:
            new, sub = key, key
        pr = probs.astype(jnp.float32)
        pr = pr / jnp.sum(pr, axis=-1, keepdims=True)
        p_flat = p_row.reshape(-1).astype(jnp.float32)
        filt = jnp.where(_nucleus_mask(pr, p_flat), pr, 0.0)
        if thr is not None:
            filt = jnp.where(filt >= thr, filt, 0.0)
        filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
        ids = jax.random.categorical(
            sub, jnp.log(jnp.maximum(filt, 1e-30)), axis=-1)
        scores = jnp.take_along_axis(
            pr, ids[:, None], axis=-1).astype(probs.dtype)
        return scores, ids[:, None].astype(jnp.int64), new

    if seed is None or int(seed) < 0:
        from ...framework.random import default_generator
        g = default_generator()
        scores, ids, newk = dispatch(
            "top_p_sampling", impl, (g.state_tensor, x, ps),
            dict(thr=thr, stateful=True), differentiable=False)
        if isinstance(newk, Tensor):
            g.state_tensor._inplace_update(newk._value)
        return scores, ids

    key = Tensor(jax.random.PRNGKey(int(seed)), _internal=True,
                 stop_gradient=True)
    scores, ids, _ = dispatch(
        "top_p_sampling", impl, (key, x, ps),
        dict(thr=thr, stateful=False), differentiable=False)
    return scores, ids
