"""paddle.incubate.optimizer: LookAhead + ModelAverage wrappers.

Reference parity: `python/paddle/incubate/optimizer/` (lookahead.py,
modelaverage.py [UNVERIFIED — empty reference mount]).  Both are
host-driven weight bookkeeping around any inner optimizer — no kernels
involved, so the TPU redesign is the same arithmetic on jnp buffers.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps of the fast optimizer, then interpolate toward the slow
    weights: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    def __getattr__(self, name):
        # object.__getattribute__ avoids infinite recursion when the
        # instance __dict__ is not yet populated (deepcopy/unpickle
        # probe attributes before __init__ runs)
        try:
            inner = object.__getattribute__(self, "inner_optimizer")
        except AttributeError:
            raise AttributeError(name) from None
        return getattr(inner, name)

    def step(self):
        params = [p for p in self.inner_optimizer._parameter_list
                  if not p.stop_gradient]
        if not self._slow:
            for p in params:
                self._slow[id(p)] = p._value
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in params:
            slow = self._slow[id(p)]
            new_slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = new_slow
            p._value = new_slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        # slow weights ride along keyed by parameter position so a
        # resume continues the interpolation trajectory
        params = [p for p in self.inner_optimizer._parameter_list
                  if not p.stop_gradient]
        for i, p in enumerate(params):
            if id(p) in self._slow:
                sd[f"lookahead_slow_{i}"] = self._slow[id(p)]
        return sd

    def set_state_dict(self, state_dict):
        sd = dict(state_dict)
        self._step_num = int(sd.pop("lookahead_step", 0))
        params = [p for p in self.inner_optimizer._parameter_list
                  if not p.stop_gradient]
        for i, p in enumerate(params):
            v = sd.pop(f"lookahead_slow_{i}", None)
            if v is not None:
                self._slow[id(p)] = jnp.asarray(
                    v._value if hasattr(v, "_value") else v)
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage:
    """Maintain an exponential/window average of the weights; swap it in
    with apply() for evaluation and back with restore()."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = [p for p in (parameters or [])
                        if not p.stop_gradient]
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum = {id(p): jnp.zeros_like(p._value)
                     for p in self._params}
        self._n = 0
        self._updates = 0
        self._backup = None

    def _window(self):
        """Effective window: everything seen so far, capped by
        max_average_window and by rate*num_updates (floored at
        min_average_window) — the reference uses the rate to decide
        when accumulated history is dropped; the streaming equivalent
        is this cap."""
        desired = max(self.min_w, int(self.rate * self._updates))
        return max(1, min(self._updates, self.max_w, desired))

    def step(self):
        """Accumulate the current weights (call after optimizer.step())."""
        self._updates += 1
        win = self._window()
        # decay only once the window is SATURATED (n already == win
        # before this sample); while it grows, plain accumulation
        saturated = self._n >= win
        if not saturated:
            self._n += 1
        for p in self._params:
            s = self._sum[id(p)]
            if saturated:
                # the reference restarts sums at the window boundary; a
                # decaying sum is the streaming equivalent
                s = s * (1.0 - 1.0 / win)
            self._sum[id(p)] = s + p._value

    def apply(self, executor=None, need_restore=True):
        if self._n == 0:
            return
        denom = min(self._n, self._window())
        if need_restore and self._backup is None:
            # never overwrite an existing backup: a second apply()
            # before restore() must not lose the training weights
            self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            p._value = (self._sum[id(p)] / denom).astype(p._value.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._value = self._backup[id(p)]
        self._backup = None
