"""Native host-runtime kernels (C, ctypes-loaded).

Reference parity: the reference's host runtime (DataLoader readers,
buffer bookkeeping) is native C++ (SURVEY.md §2.1/§2.2) [UNVERIFIED —
empty reference mount].  Here the device runtime is PJRT/XLA; the
host-side batch assembly is the piece that benefits from native code,
implemented in collate.c and compiled on first use with the system cc
(`cc -O3 -shared -fPIC`), cached under ~/.cache/paddle_tpu.  Everything
degrades to numpy when no compiler is available — `available()` tells
you which path is live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "fast_stack", "gather_rows",
           "tcp_store_available", "start_tcp_store_server",
           "stop_tcp_store_server"]

_lib = None
_tried = False
_lock = threading.Lock()
_store_lib = None
_store_tried = False



def _compile_native(src_name, so_name, compilers, flags):
    """Shared compile-with-mtime-cache-then-load step for every native
    component (collate, tcp_store, shm_ring)."""
    src = os.path.join(os.path.dirname(__file__), src_name)
    cache = os.path.join(
        os.path.expanduser(os.environ.get("PADDLE_TPU_CACHE",
                                          "~/.cache/paddle_tpu")),
        "native")
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, so_name)
    if not os.path.exists(so) or (os.path.getmtime(so)
                                  < os.path.getmtime(src)):
        tmp = f"{so}.{os.getpid()}.tmp"  # per-pid: N ranks may race here
        for cc in compilers:
            try:
                subprocess.run(
                    [cc, *flags, "-o", tmp, src],
                    check=True, capture_output=True, timeout=180)
                os.replace(tmp, so)
                break
            except (OSError, subprocess.SubprocessError):
                continue
        else:
            return None
    return ctypes.CDLL(so)


def _build_and_load():
    lib = _compile_native("collate.c", "libptnative.so",
                          ("cc", "gcc", "clang"),
                          ("-O3", "-shared", "-fPIC"))
    if lib is None:
        return None
    lib.pt_stack_copy.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_char_p]
    lib.pt_gather_rows.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p]
    return lib


def _get():
    global _lib, _tried
    if not _tried:
        with _lock:
            if not _tried:
                try:
                    _lib = _build_and_load()
                except Exception:
                    _lib = None
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def fast_stack(arrays):
    """np.stack for a list of same-shape contiguous arrays, with the
    copy loop in C (GIL released — worker threads overlap)."""
    lib = _get()
    first = np.asarray(arrays[0])
    if (lib is None or first.dtype == object
            or any(not isinstance(a, np.ndarray)
                   or a.shape != first.shape or a.dtype != first.dtype
                   for a in arrays)):
        return np.stack([np.asarray(a) for a in arrays])
    arrs = [np.ascontiguousarray(a) for a in arrays]
    n = len(arrs)
    nbytes = first.nbytes
    out = np.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_char_p * n)(*[
        ctypes.cast(a.ctypes.data, ctypes.c_char_p) for a in arrs])
    lib.pt_stack_copy(ptrs, n, nbytes,
                      out.ctypes.data_as(ctypes.c_char_p))
    return out


def _build_store():
    """Build + load the C++ TCPStore server (tcp_store.cc)."""
    lib = _compile_native("tcp_store.cc", "libpttcpstore.so",
                          ("c++", "g++", "clang++"),
                          ("-O2", "-std=c++17", "-shared", "-fPIC",
                           "-pthread"))
    if lib is None:
        return None
    lib.pt_store_server_start.restype = ctypes.c_void_p
    lib.pt_store_server_start.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
    return lib


def _get_store_lib():
    global _store_lib, _store_tried
    if not _store_tried:
        with _lock:
            if not _store_tried:
                try:
                    _store_lib = _build_store()
                except Exception:
                    _store_lib = None
                _store_tried = True
    return _store_lib


def tcp_store_available() -> bool:
    return _get_store_lib() is not None


def start_tcp_store_server(port=0):
    """Start the native TCPStore server; returns (handle, port)."""
    lib = _get_store_lib()
    if lib is None:
        raise RuntimeError("native TCPStore unavailable (no C++ "
                           "compiler); use the python fallback store")
    out_port = ctypes.c_int(0)
    h = lib.pt_store_server_start(int(port), ctypes.byref(out_port))
    if not h:
        raise RuntimeError(f"TCPStore: could not bind port {port}")
    return h, int(out_port.value)


def stop_tcp_store_server(handle):
    lib = _get_store_lib()
    if lib is not None and handle:
        lib.pt_store_server_stop(ctypes.c_void_p(handle))


def gather_rows(src, indices):
    """out[i] = src[indices[i]] over dim 0 (C memcpy per row)."""
    lib = _get()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(np.asarray(indices, np.int64))
    if (lib is None or idx.size == 0 or idx.min() < 0
            or idx.max() >= src.shape[0]):
        # numpy path also owns negative/out-of-range semantics — the C
        # memcpy must never see an unchecked index
        return src[idx]
    row = int(np.prod(src.shape[1:])) * src.dtype.itemsize
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    lib.pt_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx), row, out.ctypes.data_as(ctypes.c_char_p))
    return out


# ---------------------------------------------------------------------
# Shared-memory batch ring (shm_ring.c): the reference's C++ shared-mem
# DataLoader tensor path.  One SPSC ring per worker; numpy batch
# payloads cross process boundaries through shm instead of pickle pipes.
# ---------------------------------------------------------------------
_ring_lib = None
_ring_tried = False


def _build_ring_lib():
    lib = _compile_native("shm_ring.c", "libptshmring.so",
                          ("cc", "gcc", "clang"),
                          ("-O2", "-shared", "-fPIC", "-pthread"))
    if lib is None:
        return None
    lib.ptr_ring_create.restype = ctypes.c_void_p
    lib.ptr_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64]
    lib.ptr_ring_attach.restype = ctypes.c_void_p
    lib.ptr_ring_attach.argtypes = [ctypes.c_char_p]
    lib.ptr_ring_slot_bytes.restype = ctypes.c_int64
    lib.ptr_ring_slot_bytes.argtypes = [ctypes.c_void_p]
    lib.ptr_ring_acquire_write.restype = ctypes.c_int64
    lib.ptr_ring_acquire_write.argtypes = [ctypes.c_void_p,
                                           ctypes.c_double]
    lib.ptr_ring_commit_write.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int64]
    lib.ptr_ring_acquire_read.restype = ctypes.c_int64
    lib.ptr_ring_acquire_read.argtypes = [ctypes.c_void_p,
                                          ctypes.c_double]
    lib.ptr_ring_read_size.restype = ctypes.c_int64
    lib.ptr_ring_read_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.ptr_ring_release_read.argtypes = [ctypes.c_void_p]
    lib.ptr_ring_slot_ptr.restype = ctypes.c_void_p
    lib.ptr_ring_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.ptr_ring_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    return lib


def _get_ring_lib():
    global _ring_lib, _ring_tried
    if not _ring_tried:
        with _lock:
            if not _ring_tried:
                try:
                    _ring_lib = _build_ring_lib()
                except Exception:
                    _ring_lib = None
                _ring_tried = True
    return _ring_lib


def shm_ring_available() -> bool:
    return _get_ring_lib() is not None


class ShmRing:
    """ctypes face of shm_ring.c; create() in the parent, attach() in
    the worker.  Payloads are length-prefixed binary blobs."""

    def __init__(self, handle, lib, name, owner):
        self._h = handle
        self._lib = lib
        self.name = name
        self._owner = owner
        self.slot_bytes = lib.ptr_ring_slot_bytes(handle)

    @classmethod
    def create(cls, name, slots, slot_bytes):
        lib = _get_ring_lib()
        if lib is None:
            return None
        h = lib.ptr_ring_create(name.encode(), int(slots),
                                int(slot_bytes))
        return cls(h, lib, name, True) if h else None

    @classmethod
    def attach(cls, name):
        lib = _get_ring_lib()
        if lib is None:
            return None
        h = lib.ptr_ring_attach(name.encode())
        return cls(h, lib, name, False) if h else None

    def write(self, payload: bytes, timeout=120.0) -> bool:
        if len(payload) > self.slot_bytes:
            return False  # oversized: caller uses the pipe fallback
        slot = self._lib.ptr_ring_acquire_write(self._h, float(timeout))
        if slot < 0:
            raise TimeoutError("shm ring full")
        dst = (ctypes.c_char * self.slot_bytes).from_address(
            self._lib.ptr_ring_slot_ptr(self._h, slot))
        dst[:len(payload)] = payload
        self._lib.ptr_ring_commit_write(self._h, len(payload))
        return True

    def read(self, timeout=120.0) -> bytes:
        slot = self._lib.ptr_ring_acquire_read(self._h, float(timeout))
        if slot < 0:
            raise TimeoutError("shm ring empty")
        n = self._lib.ptr_ring_read_size(self._h, slot)
        src = (ctypes.c_char * n).from_address(
            self._lib.ptr_ring_slot_ptr(self._h, slot))
        data = bytes(src)
        self._lib.ptr_ring_release_read(self._h)
        return data

    def close(self, unlink=None):
        if self._h:
            self._lib.ptr_ring_close(
                self._h, 1 if (self._owner if unlink is None
                               else unlink) else 0)
            self._h = None
