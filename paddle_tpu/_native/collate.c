/* Native batch-assembly kernels for the host input pipeline.
 *
 * Reference parity: the reference's DataLoader core is native C++
 * (`paddle/fluid/operators/reader/`, dataloader shared-memory workers —
 * SURVEY.md §2.2 Data row [UNVERIFIED: empty reference mount]).
 *
 * TPU-native: the device side is XLA's job; what remains hot on the
 * host is assembling sample arrays into one contiguous batch that the
 * runtime can hand to the device DMA in a single transfer.  These
 * kernels run GIL-free (ctypes releases the GIL for the duration of
 * the call), so DataLoader worker threads overlap collation with
 * Python-side sample fetch.
 *
 * Built by paddle_tpu._native at first use:  cc -O3 -shared -fPIC.
 */
#include <string.h>
#include <stdint.h>

/* stack n same-sized contiguous buffers into out (batch dim 0) */
void pt_stack_copy(const char **srcs, int64_t n, int64_t nbytes,
                   char *out) {
    for (int64_t i = 0; i < n; ++i) {
        memcpy(out + i * nbytes, srcs[i], nbytes);
    }
}

/* gather rows: out[i] = src[idx[i]] for row size nbytes (host-side
 * shuffle/batch-index materialization) */
void pt_gather_rows(const char *src, const int64_t *idx, int64_t n,
                    int64_t nbytes, char *out) {
    for (int64_t i = 0; i < n; ++i) {
        memcpy(out + i * nbytes, src + idx[i] * nbytes, nbytes);
    }
}
