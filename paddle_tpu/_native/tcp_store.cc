// TCPStore: rendezvous key-value store (C++17, POSIX sockets).
//
// Role of the reference's fluid/distributed/store/tcp_store.cc
// (SURVEY.md §2.1 "Comm runtime": TCPStore KV barrier used by
// init_parallel_env rendezvous) [UNVERIFIED - empty reference mount].
//
// Design: thread-per-connection server over a mutex-protected map with
// a condition variable for blocking GET/WAIT (the reference parks
// waiting ranks the same way).  Wire format: 1-byte command,
// 4-byte LE key length + key, 8-byte LE value length + value.
// Commands: S=set, G=get(blocking), Q=query(non-blocking), A=add
// (atomic int64 counter, returns new value), W=wait(blocking until key
// exists), D=delete, N=num_keys, X=shutdown.
//
// Exposed as a C ABI (pt_store_*) loaded via ctypes by
// paddle_tpu/distributed/store.py; the server can also run in-process
// for the master rank (pt_store_server_start).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> data;
  std::atomic<bool> stop{false};
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  // live connection fds: stop() must shutdown() each so workers parked
  // in recv() unblock and join
  std::mutex conn_mu;
  std::vector<int> conn_fds;
};

bool read_n(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_key(int fd, std::string* key) {
  uint32_t klen;
  if (!read_n(fd, &klen, 4) || klen > (1u << 20)) return false;
  key->resize(klen);
  return klen == 0 || read_n(fd, key->data(), klen);
}

bool write_value(int fd, const std::string& v) {
  uint64_t vlen = v.size();
  if (!write_n(fd, &vlen, 8)) return false;
  return v.empty() || write_n(fd, v.data(), v.size());
}

void serve_conn(Store* st, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> lk(st->conn_mu);
    st->conn_fds.push_back(fd);
  }
  for (;;) {
    char cmd;
    if (!read_n(fd, &cmd, 1)) break;
    if (cmd == 'X') {
      st->stop.store(true);
      st->cv.notify_all();
      // wake the accept loop by connecting once? close listen fd below.
      ::shutdown(st->listen_fd, SHUT_RDWR);
      break;
    }
    std::string key;
    if (cmd != 'N' && !read_key(fd, &key)) break;
    if (cmd == 'S') {
      uint64_t vlen;
      if (!read_n(fd, &vlen, 8) || vlen > (1ull << 32)) break;
      std::string val(vlen, '\0');
      if (vlen && !read_n(fd, val.data(), vlen)) break;
      {
        std::lock_guard<std::mutex> lk(st->mu);
        st->data[key] = std::move(val);
      }
      st->cv.notify_all();
      char ok = 1;
      if (!write_n(fd, &ok, 1)) break;
    } else if (cmd == 'G' || cmd == 'W') {
      std::unique_lock<std::mutex> lk(st->mu);
      st->cv.wait(lk, [&] {
        return st->stop.load() || st->data.count(key) != 0;
      });
      if (st->stop.load()) break;
      std::string v = (cmd == 'G') ? st->data[key] : std::string();
      lk.unlock();
      if (cmd == 'W') {
        char ok = 1;
        if (!write_n(fd, &ok, 1)) break;
      } else if (!write_value(fd, v)) {
        break;
      }
    } else if (cmd == 'Q') {
      std::unique_lock<std::mutex> lk(st->mu);
      bool has = st->data.count(key) != 0;
      std::string v = has ? st->data[key] : std::string();
      lk.unlock();
      char flag = has ? 1 : 0;
      if (!write_n(fd, &flag, 1)) break;
      if (has && !write_value(fd, v)) break;
    } else if (cmd == 'A') {
      int64_t amount;
      if (!read_n(fd, &amount, 8)) break;
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(st->mu);
        int64_t cur = 0;
        auto it = st->data.find(key);
        if (it != st->data.end() && it->second.size() == 8) {
          std::memcpy(&cur, it->second.data(), 8);
        }
        now = cur + amount;
        std::string v(8, '\0');
        std::memcpy(v.data(), &now, 8);
        st->data[key] = std::move(v);
      }
      st->cv.notify_all();
      if (!write_n(fd, &now, 8)) break;
    } else if (cmd == 'D') {
      {
        std::lock_guard<std::mutex> lk(st->mu);
        st->data.erase(key);
      }
      char ok = 1;
      if (!write_n(fd, &ok, 1)) break;
    } else if (cmd == 'N') {
      int64_t n;
      {
        std::lock_guard<std::mutex> lk(st->mu);
        n = static_cast<int64_t>(st->data.size());
      }
      if (!write_n(fd, &n, 8)) break;
    } else {
      break;  // unknown command
    }
  }
  {
    std::lock_guard<std::mutex> lk(st->conn_mu);
    for (auto it = st->conn_fds.begin(); it != st->conn_fds.end(); ++it) {
      if (*it == fd) {
        st->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

void accept_loop(Store* st) {
  for (;;) {
    int fd = ::accept(st->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (st->stop.load()) break;
      continue;
    }
    if (st->stop.load()) {
      ::close(fd);
      break;
    }
    st->workers.emplace_back(serve_conn, st, fd);
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle, or null on failure.  port==0 picks a free
// port; *out_port receives the bound port.
void* pt_store_server_start(int port, int* out_port) {
  auto* st = new Store();
  st->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (st->listen_fd < 0) {
    delete st;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(st->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(st->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(st->listen_fd, 128) != 0) {
    ::close(st->listen_fd);
    delete st;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(st->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  if (out_port) *out_port = ntohs(addr.sin_port);
  st->accept_thread = std::thread(accept_loop, st);
  return st;
}

void pt_store_server_stop(void* handle) {
  auto* st = static_cast<Store*>(handle);
  if (!st) return;
  st->stop.store(true);
  st->cv.notify_all();
  ::shutdown(st->listen_fd, SHUT_RDWR);
  ::close(st->listen_fd);
  {
    // unblock workers parked in recv() on live client connections
    std::lock_guard<std::mutex> lk(st->conn_mu);
    for (int fd : st->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  if (st->accept_thread.joinable()) st->accept_thread.join();
  for (auto& t : st->workers) {
    if (t.joinable()) t.join();
  }
  delete st;
}

}  // extern "C"
