/* Shared-memory batch ring for DataLoader workers.
 *
 * Reference parity: the reference moves worker-produced LoDTensors
 * through C++ shared memory ("_shared_memory" tensor payloads in
 * fluid/memory + dataloader_iter's shared-mem path) instead of
 * pickling through pipes [UNVERIFIED -- empty reference mount;
 * SURVEY.md 2.2 Data row].
 *
 * Design: one single-producer single-consumer ring per worker process.
 * A POSIX shm object holds a header (ring geometry + a process-shared
 * mutex/condvar pair + head/tail cursors + per-slot byte counts)
 * followed by `slots` fixed-size slots.  The worker serializes numpy
 * batch payloads into a slot (python side writes via memoryview; only
 * tiny tokens cross the multiprocessing pipe) and the parent wraps the
 * slot memory zero-copy, copying once into batch arrays.
 *
 * Built on first use by _native/__init__.py with the system cc
 * (-O3 -shared -fPIC -lpthread); python falls back to the pipe path
 * when no compiler or no POSIX shm is available.
 */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

typedef struct {
    int64_t magic;
    int64_t slots;
    int64_t slot_bytes;
    int64_t head;      /* next slot to read  */
    int64_t tail;      /* next slot to write */
    int64_t count;     /* filled slots       */
    pthread_mutex_t mu;
    pthread_cond_t not_full;
    pthread_cond_t not_empty;
    int64_t used[1];   /* per-slot payload byte counts (slots entries) */
} ring_header;

typedef struct {
    ring_header *hdr;
    char *base;        /* first slot */
    size_t map_bytes;
    char name[128];
    int owner;
} ring;

#define RING_MAGIC 0x70746E72696E6731LL

static size_t header_bytes(int64_t slots) {
    return sizeof(ring_header) + (size_t)(slots - 1) * sizeof(int64_t);
}

/* lock handling EOWNERDEAD: mark consistent and continue — ring
 * cursors may be off by the dead process's half-done operation, but
 * the parent's python-level timeout then surfaces instead of a
 * permanent wedge */
static int lock_mu(ring_header *h) {
    int rc = pthread_mutex_lock(&h->mu);
    if (rc == EOWNERDEAD) {
        pthread_mutex_consistent(&h->mu);
        rc = 0;
    }
    return rc;
}

static void abs_deadline(struct timespec *ts, double timeout) {
    clock_gettime(CLOCK_REALTIME, ts);
    ts->tv_sec += (time_t)timeout;
    ts->tv_nsec += (long)((timeout - (time_t)timeout) * 1e9);
    if (ts->tv_nsec >= 1000000000L) {
        ts->tv_sec += 1;
        ts->tv_nsec -= 1000000000L;
    }
}

void *ptr_ring_create(const char *name, int64_t slots,
                      int64_t slot_bytes) {
    shm_unlink(name); /* stale object from a crashed run */
    int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return NULL;
    size_t hb = header_bytes(slots);
    /* slot area starts at a 64-byte boundary */
    size_t off = (hb + 63) & ~((size_t)63);
    size_t total = off + (size_t)slots * (size_t)slot_bytes;
    if (ftruncate(fd, (off_t)total) != 0) {
        close(fd);
        shm_unlink(name);
        return NULL;
    }
    void *mem = mmap(NULL, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
    close(fd);
    if (mem == MAP_FAILED) {
        shm_unlink(name);
        return NULL;
    }
    ring_header *h = (ring_header *)mem;
    memset(h, 0, hb);
    h->slots = slots;
    h->slot_bytes = slot_bytes;

    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    /* robust: a worker killed while holding the mutex must not wedge
     * the parent (PTHREAD_MUTEX_ROBUST is an enum, not a macro — call
     * unconditionally; glibc and musl both provide it) */
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->not_full, &ca);
    pthread_cond_init(&h->not_empty, &ca);
    h->magic = RING_MAGIC;

    ring *r = calloc(1, sizeof(ring));
    r->hdr = h;
    r->base = (char *)mem + off;
    r->map_bytes = total;
    snprintf(r->name, sizeof(r->name), "%s", name);
    r->owner = 1;
    return r;
}

void *ptr_ring_attach(const char *name) {
    int fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return NULL;
    struct stat st;
    if (fstat(fd, &st) != 0) {
        close(fd);
        return NULL;
    }
    void *mem = mmap(NULL, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return NULL;
    ring_header *h = (ring_header *)mem;
    if (h->magic != RING_MAGIC) {
        munmap(mem, (size_t)st.st_size);
        return NULL;
    }
    size_t off = (header_bytes(h->slots) + 63) & ~((size_t)63);
    ring *r = calloc(1, sizeof(ring));
    r->hdr = h;
    r->base = (char *)mem + off;
    r->map_bytes = (size_t)st.st_size;
    snprintf(r->name, sizeof(r->name), "%s", name);
    r->owner = 0;
    return r;
}

int64_t ptr_ring_slot_bytes(void *rp) {
    return ((ring *)rp)->hdr->slot_bytes;
}

/* returns slot index to fill, or -1 on timeout */
int64_t ptr_ring_acquire_write(void *rp, double timeout) {
    ring *r = rp;
    ring_header *h = r->hdr;
    struct timespec ts;
    abs_deadline(&ts, timeout);
    if (lock_mu(h) != 0) return -1;
    while (h->count == h->slots) {
        int rc = pthread_cond_timedwait(&h->not_full, &h->mu, &ts);
        if (rc == EOWNERDEAD) {
            pthread_mutex_consistent(&h->mu);
            rc = 0;
        }
        if (rc != 0) {
            pthread_mutex_unlock(&h->mu);
            return -1;
        }
    }
    int64_t slot = h->tail;
    pthread_mutex_unlock(&h->mu);
    return slot;
}

void ptr_ring_commit_write(void *rp, int64_t nbytes) {
    ring *r = rp;
    ring_header *h = r->hdr;
    if (lock_mu(h) != 0) return;
    h->used[h->tail] = nbytes;
    h->tail = (h->tail + 1) % h->slots;
    h->count += 1;
    pthread_cond_signal(&h->not_empty);
    pthread_mutex_unlock(&h->mu);
}

/* returns readable slot index, or -1 on timeout */
int64_t ptr_ring_acquire_read(void *rp, double timeout) {
    ring *r = rp;
    ring_header *h = r->hdr;
    struct timespec ts;
    abs_deadline(&ts, timeout);
    if (lock_mu(h) != 0) return -1;
    while (h->count == 0) {
        int rc = pthread_cond_timedwait(&h->not_empty, &h->mu, &ts);
        if (rc == EOWNERDEAD) {
            pthread_mutex_consistent(&h->mu);
            rc = 0;
        }
        if (rc != 0) {
            pthread_mutex_unlock(&h->mu);
            return -1;
        }
    }
    int64_t slot = h->head;
    pthread_mutex_unlock(&h->mu);
    return slot;
}

int64_t ptr_ring_read_size(void *rp, int64_t slot) {
    return ((ring *)rp)->hdr->used[slot];
}

void ptr_ring_release_read(void *rp) {
    ring *r = rp;
    ring_header *h = r->hdr;
    if (lock_mu(h) != 0) return;
    h->head = (h->head + 1) % h->slots;
    h->count -= 1;
    pthread_cond_signal(&h->not_full);
    pthread_mutex_unlock(&h->mu);
}

char *ptr_ring_slot_ptr(void *rp, int64_t slot) {
    ring *r = rp;
    return r->base + (size_t)slot * (size_t)r->hdr->slot_bytes;
}

void ptr_ring_close(void *rp, int unlink_it) {
    ring *r = rp;
    if (unlink_it) shm_unlink(r->name);
    munmap((void *)r->hdr, r->map_bytes);
    free(r);
}
