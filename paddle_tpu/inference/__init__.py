"""paddle.inference: the deployment API (Config / create_predictor).

Role of the reference's AnalysisPredictor stack
(`paddle/fluid/inference/api/analysis_predictor.cc`, python surface
`paddle/inference/__init__.py` [UNVERIFIED — empty reference mount]):
load a saved inference artifact, bind named input/output handles, and
run it without any model python code.

TPU-native redesign: the artifact's "program" is a serialized
`jax.export` StableHLO blob (written by `paddle.jit.save` or
`paddle.static.save_inference_model`), lowered for BOTH cpu and tpu at
save time.  The predictor deserializes it once and calls the compiled
executable; there is no IR-analysis pass pipeline to run at load time —
XLA already performed fusion/layout/memory planning, which is the
AnalysisPredictor pass stack's job in the reference.  Config toggles
that control CUDA/TensorRT/MKLDNN specifics are accepted for API
compatibility and recorded, but the execution path is always the XLA
executable (see each method's docstring).
"""
from __future__ import annotations

import os
import pickle
import threading

import numpy as np

__all__ = [
    "Config", "Predictor", "Tensor", "create_predictor",
    "get_version", "PredictorPool", "PlaceType", "DataType",
]


def get_version() -> str:
    from .. import __version__
    return __version__


class PlaceType:
    kHost = 0
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kCUSTOM = 3  # the TPU artifact runs under this place in spirit


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    INT8 = "int8"
    BOOL = "bool"


class Config:
    """Inference configuration.

    Mirrors the reference Config surface.  Device/IR knobs that steer
    CUDA/TensorRT/oneDNN in the reference are no-ops here (XLA owns
    fusion and memory planning); they are kept so deployment scripts
    port unchanged, and `summary()` reports what was requested.
    """

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and params_file is None \
                and os.path.isdir(prog_file):
            self._model_dir = prog_file
            self._prog_file = None
            self._params_file = None
        else:
            self._model_dir = None
            self._prog_file = prog_file
            self._params_file = params_file
        self._use_gpu = False
        self._mem_optim = True
        self._ir_optim = True
        self._glog_info = True
        self._cpu_threads = 1
        self._extra = {}

    # -- model location -------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        if params_file is None and os.path.isdir(prog_file):
            self._model_dir, self._prog_file = prog_file, None
        else:
            self._prog_file, self._params_file = prog_file, params_file

    def set_prog_file(self, f):
        self._prog_file = f

    def set_params_file(self, f):
        self._params_file = f

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def path_prefix(self):
        """Common prefix of the artifact files (.pdmodel/.pdiparams/.pdexec)."""
        if self._prog_file:
            p = self._prog_file
            for suf in (".pdmodel", ".pdiparams"):
                if p.endswith(suf):
                    return p[: -len(suf)]
            return p
        if self._model_dir:
            # first *.pdmodel in the dir
            for fn in sorted(os.listdir(self._model_dir)):
                if fn.endswith(".pdmodel"):
                    return os.path.join(self._model_dir, fn[: -len(".pdmodel")])
        raise ValueError("Config has no model set (set_model / __init__)")

    # -- device selection (recorded; execution is backend-agnostic) -----
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        """Accepted for compatibility.  The executable runs on whatever
        backend jax selected (TPU when available); there is no CUDA
        memory pool to size."""
        self._use_gpu = True

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self):
        return self._use_gpu

    def gpu_device_id(self):
        return 0

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    def cpu_math_library_num_threads(self):
        return self._cpu_threads

    # -- pass/IR knobs (XLA owns these; recorded only) -------------------
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._mem_optim = bool(flag)

    def enable_mkldnn(self):
        self._extra["mkldnn"] = True

    def enable_tensorrt_engine(self, **kwargs):
        self._extra["tensorrt"] = kwargs

    def switch_use_feed_fetch_ops(self, flag):
        self._extra["feed_fetch_ops"] = bool(flag)

    def switch_specify_input_names(self, flag=True):
        self._extra["specify_input_names"] = bool(flag)

    def disable_glog_info(self):
        self._glog_info = False

    def glog_info_disabled(self):
        return not self._glog_info

    def summary(self):
        lines = [
            f"model path prefix: {self.path_prefix()}",
            f"requested device: {'gpu' if self._use_gpu else 'cpu'} "
            f"(actual: jax default backend)",
            f"ir_optim(recorded): {self._ir_optim}",
            f"memory_optim(recorded): {self._mem_optim}",
        ]
        for k, v in self._extra.items():
            lines.append(f"{k}(recorded): {v}")
        return "\n".join(lines)


class Tensor:
    """Named input/output handle bound to a Predictor slot.

    The reference's inference `Tensor` wraps a device buffer with
    copy_from_cpu / copy_to_cpu; here the device transfer happens when
    the executable runs (inputs) or when copy_to_cpu is called
    (outputs — the jax array is device-resident until then)."""

    def __init__(self, name, shape=None, dtype=None):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._dtype = dtype
        self._host = None     # np.ndarray staged by copy_from_cpu
        self._device = None   # jax array produced by run()

    # inputs ------------------------------------------------------------
    def reshape(self, shape):
        self._shape = list(int(s) for s in shape)

    def copy_from_cpu(self, data):
        data = np.ascontiguousarray(data)
        if self._dtype is not None:
            from ..core.dtypes import convert_dtype
            data = data.astype(convert_dtype(self._dtype).np_dtype,
                               copy=False)
        self._host = data
        self._shape = list(data.shape)

    def share_external_data(self, data):
        self.copy_from_cpu(np.asarray(data))

    # outputs -----------------------------------------------------------
    def copy_to_cpu(self):
        if self._device is None:
            raise RuntimeError(
                f"output {self.name!r} has no value; call predictor.run() "
                "first")
        return np.asarray(self._device)

    def shape(self):
        if self._device is not None:
            return list(self._device.shape)
        return list(self._shape or [])

    def type(self):
        if self._device is not None:
            return str(self._device.dtype)
        return self._dtype


class Predictor:
    """Executes a saved inference artifact through named handles.

    Usage (identical to the reference):
        config = paddle.inference.Config(prefix + ".pdmodel",
                                         prefix + ".pdiparams")
        pred = paddle.inference.create_predictor(config)
        inp = pred.get_input_handle(pred.get_input_names()[0])
        inp.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        y = out.copy_to_cpu()
    """

    def __init__(self, config: Config):
        self._config = config
        prefix = config.path_prefix()
        with open(prefix + ".pdmodel", "rb") as f:
            self._meta = pickle.load(f)
        params_path = (config.params_file() or prefix + ".pdiparams")
        self._state = {}
        if os.path.exists(params_path):
            with open(params_path, "rb") as f:
                self._state = pickle.load(f)
        exec_path = prefix + ".pdexec"
        if not os.path.exists(exec_path):
            raise RuntimeError(
                f"{exec_path} not found: this artifact carries no "
                "compiled forward.  Re-save with paddle.jit.save(layer, "
                "prefix, input_spec=[...]) or "
                "paddle.static.save_inference_model(...)")
        with open(exec_path, "rb") as f:
            blob = f.read()
        from jax import export as jexport
        self._exported = jexport.deserialize(blob)
        self._lock = threading.Lock()

        import jax.numpy as jnp
        names = self._meta.get("state_names") or sorted(self._state)
        self._state_vals = tuple(jnp.asarray(self._state[k]) for k in names)

        in_names = self._meta.get("input_names")
        spec = self._meta.get("input_spec") or []
        if not in_names:
            in_names = [f"x{i}" for i in range(len(spec))]
        self._inputs = {}
        for i, n in enumerate(in_names):
            shape, dtype = (spec[i] if i < len(spec) else (None, None))
            self._inputs[n] = Tensor(n, shape, dtype)
        self._input_order = list(in_names)

        out_names = self._meta.get("output_names")
        if not out_names:
            n_out = len(self._exported.out_avals)
            out_names = [f"out{i}" for i in range(n_out)]
        self._outputs = {n: Tensor(n) for n in out_names}
        self._output_order = list(out_names)

    # -- introspection ---------------------------------------------------
    def get_input_names(self):
        return list(self._input_order)

    def get_output_names(self):
        return list(self._output_order)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    # -- execution -------------------------------------------------------
    def run(self, inputs=None):
        """Run the executable.  Either stage inputs through the handles
        (reference style) or pass a list of arrays positionally."""
        import jax.numpy as jnp
        # the lock protects only the handle state (input staging, output
        # binding): the executable itself is a pure function of
        # (state_vals, xs), so concurrent run() calls overlap on device
        # instead of serializing the whole step
        with self._lock:
            if inputs is not None:
                for n, x in zip(self._input_order, inputs):
                    self._inputs[n].copy_from_cpu(np.asarray(x))
            xs = []
            for n in self._input_order:
                h = self._inputs[n]
                if h._host is None:
                    raise RuntimeError(
                        f"input {n!r} not set: call "
                        f"get_input_handle({n!r}).copy_from_cpu(...)")
                xs.append(jnp.asarray(h._host))
        out = self._exported.call(self._state_vals, *xs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        with self._lock:
            for n, o in zip(self._output_order, out):
                self._outputs[n]._device = o
        # build the return from this call's own results, not the shared
        # handles — a concurrent run() may rebind them immediately
        return [np.asarray(o) for o in out]

    def clear_intermediate_tensor(self):
        pass  # XLA frees intermediates at executable exit

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """A fixed-size pool of predictors sharing ONE deserialized
    executable + weight buffers (the reference uses this for
    multi-threaded serving).  Each member only has its own input/output
    handles and lock."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._preds = [first]
        for _ in range(max(1, size) - 1):
            clone = Predictor.__new__(Predictor)
            clone.__dict__.update(first.__dict__)
            clone._lock = threading.Lock()
            clone._inputs = {n: Tensor(n, h._shape, h._dtype)
                             for n, h in first._inputs.items()}
            clone._outputs = {n: Tensor(n) for n in first._outputs}
            self._preds.append(clone)

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx % len(self._preds)]
