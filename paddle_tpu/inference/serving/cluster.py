"""Multi-host serving fabric: gossiped prefix routing + autoscaling.

One :class:`ClusterRouter` fronts N *hosts* — each a full colocated
:class:`~.engine.GenerationEngine` replica with its own paged KV pool
— and extends the single-process serving stack (dp.py's affinity
routing, disagg.py's block-granular handoffs, the PR-15 elastic
machinery) across a simulated host boundary:

**Gossiped prefix affinity.**  ``prefix_match_tokens`` needs the
pool's chain-hash index, which on a remote host is not addressable.
Each host therefore publishes a compact digest of its prefix index
(:meth:`~.kv_cache.PagedKVCache.prefix_digest` — the chain hashes of
both tiers) through the rendezvous store on a heartbeat.  The router
hashes an incoming prompt once (``chain_hashes``) and scores every
host by how many leading links its *gossiped* digest holds.  The
contract: a summary older than ``staleness_s`` scores zero, and a
digest is a ROUTING HINT ONLY — a stale or wrong hint routes to a
host that misses its prefix cache and re-prefills, which is slower,
never wrong.  Correctness always re-derives from the chosen host's
actual index.  (In this in-process simulation the chain hashes come
from Python's salted ``hash`` and are only comparable within one
process; a real deployment would swap in a process-stable hash — the
gossip contract is unchanged.)

**Failover = replay.**  Per-host :class:`~.dp.ReplicaHealth` machines
(the PR-12 transitions) gate stepping and routing.  When a host dies
mid-step (``fabric.host_down.h<i>``), its waiting AND running
requests are harvested — committed progress folds into the prompt via
``scheduler.requeue`` — and resubmitted on survivors.  Sampling is
keyed by ``fold_in(seed, absolute_position)``, so the replay is
bit-identical: the cluster's output with a mid-burst host kill equals
the no-kill run token for token, greedy or seeded.

**Autoscaling = the same drain, driven by pressure.**  The autoscaler
watches aggregate queue depth: sustained pressure activates a spare
host (scale-up), sustained idleness drains one (scale-down).  A
*preemption notice* (``fabric.preempt.h<i>``, the TPU-pool eviction
signal) takes exactly the scale-down path: extract every decodable
request's KV as a :class:`~.tiering.HandoffPayload`, ship it over the
fabric transport (transport.py wire bytes — the prefix-cache value
leaves WITH the host), replay the rest, and re-legalize any attached
:class:`~..distributed.auto_parallel.sharding.MeshPlan` via
``shrink()`` so a training-style mesh riding the same pool stays
legal.  Every move records ``fabric.scale_event`` instants and
``serving.cluster_failover_ms`` so ``phase_breakdown()`` surfaces
them next to the fabric transfer lane.

**Degraded mode = routing on the last snapshot.**  Gossip is a HINT,
so the router never needs the store to be *correct* — only to be
*fresh*.  When the store is unreachable (a real outage, or the
``store.partition.h<i>`` fault site simulating one host partitioned
away), every store access degrades instead of propagating: routing
falls back to the last gossiped digest snapshot (staleness waived —
a stale hint costs a re-prefill, never a wrong token), publishes are
skipped, and the autoscaler PAUSES (scale decisions need a quorum
view the router no longer has).  The degraded window is metered
(``cluster.degraded_ms`` histogram, ``cluster:degraded`` span in the
``degraded`` lane of ``phase_breakdown()``).  When the store is a
:class:`~...distributed.store.ResilientStore`, the router holds an
epoch-stamped lease: a publish fenced with ``StoreEpochError`` after
a standby promotion renews the lease and retries — only a writer
that can still REACH the store can renew, so a partitioned twin
stays fenced out.
"""
from __future__ import annotations

import json
import time
from collections import deque

from ... import observability as obs
from ...distributed.fault_tolerance.plan import fault_point
from ...distributed.store import LocalStore, StoreEpochError
from .dp import ReplicaHealth
from .engine import GenerationEngine
from .errors import ServingUnavailable
from .transport import LoopbackTransport, serialize_handoff

__all__ = ["ClusterRouter", "LocalStore"]


class ClusterRouter:
    """Multi-host serving front (module doc).

    ``hosts`` replicas are active at start; ``spare_hosts`` more can
    be activated by the autoscaler (their engines are built lazily on
    first activation, so an unused spare costs nothing).  All engines
    split one colocated engine's HBM budget unless ``hbm_fraction``
    says otherwise."""

    def __init__(self, model, hosts=2, spare_hosts=0, store=None,
                 transport=None, staleness_s=2.0, heartbeat_s=0.25,
                 autoscale=False, min_hosts=1, scale_up_depth=8,
                 scale_down_idle_steps=64, mesh_plan=None,
                 hbm_fraction=None, fail_threshold=1,
                 probation_policy=None, clock=None, **engine_kwargs):
        self.n_hosts = int(hosts) + int(spare_hosts)
        if int(hosts) < 1:
            raise ValueError(f"need at least one host, got {hosts}")
        self.model = model
        self.clock = clock or time.monotonic
        self.store = store if store is not None else LocalStore()
        self.transport = transport or LoopbackTransport()
        self.staleness_s = float(staleness_s)
        self.heartbeat_s = float(heartbeat_s)
        self.autoscale = bool(autoscale)
        self.min_hosts = int(min_hosts)
        self.scale_up_depth = int(scale_up_depth)
        self.scale_down_idle_steps = int(scale_down_idle_steps)
        self.mesh_plan = mesh_plan
        if hbm_fraction is None:
            hbm_fraction = 0.3 / self.n_hosts
        self._engine_kwargs = dict(engine_kwargs,
                                   hbm_fraction=hbm_fraction)
        self._engines = [None] * self.n_hosts
        self._active = [i < int(hosts) for i in range(self.n_hosts)]
        self.health = [
            ReplicaHealth(f"host{i}", policy=probation_policy,
                          fail_threshold=fail_threshold,
                          clock=self.clock)
            for i in range(self.n_hosts)
        ]
        for i in range(int(hosts)):
            self._ensure_engine(i)
            self.transport.connect(f"host{i}")
        self._owner = {}       # req_id -> ("host", i) | ("fabric", i)
        self._exports = {}     # req_id -> export sequence (dedup key)
        self._inflight = deque()   # [delivery, target, req, stream]
        self._results = {}
        self._last_gossip = [0.0] * self.n_hosts
        self._idle_steps = 0
        self._req_counter = 0
        self.failovers = 0
        self.replays = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.preemptions = 0
        # degraded-mode state: last good gossip record per host, and
        # the open outage window (None when the store is reachable)
        self._digest_cache = {}
        self._degraded_t0 = None       # perf_counter at entry
        self._degraded_mono = None     # self.clock() at entry
        self.degraded_ms = 0.0
        self.degraded_events = 0
        self.fenced_writes = 0
        self._lease = self.store.acquire_lease(owner="router") \
            if hasattr(self.store, "acquire_lease") else None

    # -- hosts -----------------------------------------------------------
    def _ensure_engine(self, i):
        if self._engines[i] is None:
            self._engines[i] = GenerationEngine(
                self.model, role="colocated",
                resident_name=f"kv cache blocks (host{i})",
                **self._engine_kwargs)
        return self._engines[i]

    def _eligible(self, exclude=()):
        return [i for i in range(self.n_hosts)
                if self._active[i] and i not in exclude
                and self.health[i].eligible()]

    @staticmethod
    def _load(eng):
        return (eng.scheduler.queue_depth + len(eng.scheduler.running)
                + len(eng._pending))

    # -- degraded mode ---------------------------------------------------
    _STORE_DOWN = (ConnectionError, OSError, TimeoutError)

    def _store_call(self, i, fn):
        """One store access on behalf of host ``i``'s view.  The
        ``store.partition.h<i>`` fault site simulates this host being
        partitioned from the rendezvous master; any unreachability
        (injected or real) flips the router DEGRADED instead of
        propagating.  Returns ``(result, reachable)``."""
        try:
            fault_point(f"store.partition.h{i}")
            out = fn()
        except self._STORE_DOWN as e:
            self._enter_degraded(e)
            return None, False
        self._exit_degraded()
        return out, True

    def _enter_degraded(self, err):
        if self._degraded_t0 is not None:
            return
        self._degraded_t0 = time.perf_counter()
        self._degraded_mono = self.clock()
        self.degraded_events += 1
        obs.get_registry().counter("cluster.degraded_events").inc()
        obs.instant("cluster.degraded", cat="degraded",
                    error=f"{type(err).__name__}: {err}"[:200])

    def _exit_degraded(self):
        if self._degraded_t0 is None:
            return
        t0, self._degraded_t0 = self._degraded_t0, None
        dur = max(0.0, time.perf_counter() - t0)
        self.degraded_ms += max(
            0.0, (self.clock() - self._degraded_mono) * 1e3)
        self._degraded_mono = None
        tl = obs.get_timeline()
        tl.add_span("cluster:degraded", cat="degraded",
                    ts=t0 - tl.t0, dur=dur)
        obs.get_registry().histogram("cluster.degraded_ms").observe(
            dur * 1e3)

    @property
    def degraded(self):
        return self._degraded_t0 is not None

    # -- gossip ----------------------------------------------------------
    def _publish(self, i):
        """One heartbeat: this host's prefix digest into the store.
        Fenced writes (a standby was promoted since our lease) renew
        and retry; an unreachable store skips the publish — the local
        snapshot still refreshes, so degraded routing stays current
        for this host's own view."""
        eng = self._engines[i]
        dig = eng.cache.prefix_digest()
        record = {"t": self.clock(), "commit_gen": dig["commit_gen"],
                  "block_size": dig["block_size"],
                  "hashes": list(dig["hashes"])}
        data = json.dumps(record).encode()
        key = f"fabric/prefix/host{i}"

        def write():
            if self._lease is None:
                self.store.set(key, data)
                return
            try:
                self.store.set(key, data, lease=self._lease)
            except StoreEpochError:
                self.fenced_writes += 1
                obs.get_registry().counter(
                    "cluster.fenced_writes").inc()
                self._lease = self.store.renew(self._lease)
                self.store.set(key, data, lease=self._lease)

        _, reachable = self._store_call(i, write)
        self._digest_cache[i] = record
        self._last_gossip[i] = self.clock()
        if reachable:
            obs.get_registry().counter("fabric.gossip_published").inc()

    def _gossip_affinity(self, i, hashes):
        """Leading-prefix token match of ``hashes`` against host i's
        LAST PUBLISHED digest.  Stale (> staleness_s) or absent
        summaries score 0 — a hint gone quiet stops attracting
        traffic, it never blocks it.  With the store unreachable the
        staleness bound is WAIVED over the cached snapshot: hints are
        correctness-safe, and during an outage an old hint beats
        none."""
        raw, reachable = self._store_call(
            i, lambda: self.store.query(f"fabric/prefix/host{i}"))
        if not reachable:
            record = self._digest_cache.get(i)
            if record is None:
                return 0
            obs.get_registry().counter("cluster.degraded_routes").inc()
        else:
            if raw is None:
                return 0
            record = json.loads(raw)
            self._digest_cache[i] = record
            if self.clock() - float(record["t"]) > self.staleness_s:
                obs.get_registry().counter("fabric.gossip_stale").inc()
                return 0
        known = set(record["hashes"])
        depth = 0
        for h in hashes:
            if h not in known:
                break
            depth += 1
        return depth * int(record["block_size"])

    def _route(self, tokens, exclude=(), adapter=None):
        """dp.py's affinity-with-skew-guard routing, with the affinity
        term coming from GOSSIP instead of a shared-address-space
        index probe."""
        eligible = self._eligible(exclude)
        if not eligible:
            raise ServingUnavailable(
                f"no healthy host available (all {self.n_hosts} are "
                "inactive or backing off)")
        loads = {i: self._load(self._engines[i]) for i in eligible}
        min_load = min(loads.values())
        hashes = self._engines[eligible[0]].cache.chain_hashes(
            tokens, adapter=adapter)
        aff = {i: self._gossip_affinity(i, hashes) for i in eligible}
        best = max(eligible, key=lambda i: (aff[i], -loads[i], -i))
        if (aff[best] > 0 and loads[best] - min_load
                <= self._engines[best].max_batch):
            if aff[best] > 0:
                obs.get_registry().counter(
                    "fabric.gossip_routed").inc()
            return best
        return min(eligible, key=lambda i: (loads[i], i))

    # -- public API ------------------------------------------------------
    def add_request(self, prompt, request_id=None, **kwargs):
        if request_id is None:
            request_id = f"clreq{self._req_counter}"
        self._req_counter += 1
        prompt_list = [int(t) for t in prompt]
        i = self._route(prompt_list, adapter=kwargs.get("adapter"))
        with obs.tag(shard=f"host{i}"):
            self._engines[i].add_request(prompt_list,
                                         request_id=request_id,
                                         **kwargs)
        self._owner[request_id] = ("host", i)
        return request_id

    def has_unfinished(self):
        return (bool(self._inflight)
                or any(self._active[i] and self._engines[i] is not None
                       and self._engines[i].has_unfinished()
                       for i in range(self.n_hosts)))

    def step(self):
        """One cluster step: autoscale check, advance every active
        host (preemption notices and hard deaths handled per host),
        then seat in-flight fabric payloads — AFTER the host loop, so
        a transfer's span brackets the decode dispatches it hid
        behind."""
        self._autoscale_tick()
        finished = []
        for i in range(self.n_hosts):
            if not (self._active[i] and self.health[i].eligible()):
                continue
            eng = self._engines[i]
            try:
                fault_point(f"fabric.preempt.h{i}")
            except Exception as e:
                self.preemptions += 1
                self._scale_down(i, reason="preempt", error=e)
                continue
            now = self.clock()
            if now - self._last_gossip[i] >= self.heartbeat_s:
                self._publish(i)
            if not eng.has_unfinished():
                continue
            try:
                with obs.tag(shard=f"host{i}"):
                    fault_point(f"fabric.host_down.h{i}")
                    finished.extend(eng.step())
                self.health[i].record_success()
            except Exception as e:
                self._host_failover(i, e)
        self._pump_fabric()
        for req in finished:
            self._finish(req)
        return finished

    # -- fabric seating --------------------------------------------------
    def _ship(self, src, req, exclude=()):
        """Extract one decodable request's KV off host ``src`` and
        ship it over the fabric to the routed survivor."""
        eng = self._engines[src]
        payload, length, stream = eng.extract_request(req)
        tokens = (list(req.prompt) + list(req.generated))[:length]
        target = self._route(tokens, exclude=exclude)
        n = self._exports.get(req.id, 0) + 1
        self._exports[req.id] = n
        data = serialize_handoff(
            payload, request_id=req.id,
            commit_gen=eng.cache._commit_gen, length=length,
            stream=stream, request=req, meta={"export": n})
        self.transport.send(f"host{target}", data,
                            oob={"request": req, "stream": stream})
        for d in self.transport.recv(f"host{target}"):
            self._inflight.append([d, target, d.oob.get("request"),
                                   d.oob.get("stream")])
        self._owner[req.id] = ("fabric", target)
        return target

    def _pump_fabric(self):
        """Seat delivered payloads; a host with no free row keeps the
        delivery queued (host-side bytes, no HBM) for the next step."""
        retry = deque()
        while self._inflight:
            item = self._inflight.popleft()
            delivery, target, req, stream = item
            env = delivery.envelope
            if req is None:
                req = env.restore_request()
            if stream is None and env.stream_state is not None:
                stream = env.restore_stream()
            placed = False
            if self._active[target] and self.health[target].eligible():
                with obs.tag(shard=f"host{target}"):
                    placed = self._engines[target].inject_request(
                        req, env.length, env.payload, stream=stream)
            else:
                # adoptive host died while the payload was in flight:
                # replay from scratch on whoever is left
                self._requeue_refugee(req, stream)
                continue
            if placed:
                delivery.settle()
                self._owner[req.id] = ("host", target)
                obs.get_registry().counter("fabric.handoffs").inc()
            else:
                retry.append(item)
        self._inflight.extend(retry)

    def _requeue_refugee(self, req, stream):
        """Replay a request whose KV payload cannot seat anywhere
        (target lost mid-flight): fold committed tokens into the
        prompt and resubmit — bit-identical by absolute position."""
        req.prompt = list(req.prompt) + [int(t) for t in req.generated]
        req.stream_offset += len(req.generated)
        req.max_new_tokens -= len(req.generated)
        req.generated = []
        req.n_scheduled = 0
        req.num_computed = 0
        req.cached_prefix = 0
        req.row = None
        req.preemptions += 1
        i = self._route(req.prompt, adapter=req.adapter)
        self._engines[i].scheduler.submit(req)
        if stream is not None:
            self._engines[i]._streams[req.id] = stream
        self._owner[req.id] = ("host", i)
        self.replays += 1

    # -- failover --------------------------------------------------------
    def _harvest(self, eng):
        """disagg.py's harvest: requeue running (progress folds into
        the prompt), collect waiting; returns requests to replay."""
        for req in list(eng.scheduler.running):
            if req.row is not None:
                eng._rows[req.row] = None
            eng._lora_release(req)
            if eng.proposer is not None:
                eng.proposer.drop(req.id)
            eng.scheduler.requeue(req, req.generated)
        eng._pending.clear()
        moved = list(eng.scheduler.waiting)
        eng.scheduler.waiting.clear()
        return moved

    def _replay(self, src, moved, exclude, t0, kind, error):
        eng = self._engines[src]
        try:
            for req in moved:
                i = self._route(req.prompt, exclude=exclude,
                                adapter=req.adapter)
                self._engines[i].scheduler.submit(req)
                self._owner[req.id] = ("host", i)
                st = eng._streams.pop(req.id, None)
                if st is not None:
                    self._engines[i]._streams[req.id] = st
        except ServingUnavailable:
            for req in reversed(moved):
                if self._owner.get(req.id, ("x",))[0] != "host" \
                        or self._owner[req.id][1] == src:
                    eng.scheduler.waiting.appendleft(req)
            raise
        recovery_ms = (self.clock() - t0) * 1e3
        self.failovers += 1
        self.replays += len(moved)
        reg = obs.get_registry()
        reg.counter("serving.failovers").inc()
        reg.counter("serving.replays").inc(len(moved))
        reg.histogram("serving.cluster_failover_ms").observe(recovery_ms)
        obs.instant("serving.cluster_failover", cat="fault",
                    host=f"host{src}", kind=kind, replayed=len(moved),
                    recovery_ms=round(recovery_ms, 3),
                    error=f"{type(error).__name__}: {error}"[:200])

    def _host_failover(self, i, error):
        """Hard host death: its HBM (and so its KV) is GONE — nothing
        to ship.  Harvest the scheduler state the front still owns
        and replay on survivors; shrink any attached mesh plan."""
        t0 = self.clock()
        self.health[i].record_failure()
        moved = self._harvest(self._engines[i])
        self._shrink_mesh(i)
        self._replay(i, moved, exclude=(i,), t0=t0, kind="host_down",
                     error=error)

    # -- autoscaler ------------------------------------------------------
    def _autoscale_tick(self):
        if not self.autoscale:
            return
        if self.degraded:
            # scale decisions gossip through the store; without it we
            # neither add capacity nor drain — routing continues on
            # snapshots, autoscaling resumes when the store does
            return
        active = self._eligible()
        if not active:
            return
        depth = sum(self._load(self._engines[i]) for i in active)
        spares = [i for i in range(self.n_hosts) if not self._active[i]]
        if spares and depth / len(active) >= self.scale_up_depth:
            self._scale_up(spares[0])
            self._idle_steps = 0
        elif depth == 0 and len(active) > self.min_hosts:
            self._idle_steps += 1
            if self._idle_steps >= self.scale_down_idle_steps:
                self._scale_down(active[-1], reason="idle")
                self._idle_steps = 0
        else:
            self._idle_steps = 0

    def _scale_event(self, kind, host, **attrs):
        reg = obs.get_registry()
        reg.counter("fabric.scale_events").inc()
        obs.instant("fabric.scale_event", cat="fault", kind=kind,
                    host=f"host{host}", **attrs)

    def _scale_up(self, i):
        """Activate a spare (lazily building its engine), announce it
        via gossip so affinity traffic can find it."""
        self._ensure_engine(i)
        self.transport.connect(f"host{i}")
        self._active[i] = True
        self.scale_ups += 1
        self._publish(i)
        self._scale_event("up", i,
                          active=sum(self._active))

    def _scale_down(self, i, reason, error=None):
        """Drain host ``i`` and deactivate it: decodable requests'
        KV ships over the fabric (the prefix-cache value leaves with
        them), everything else replays from its folded prompt.  A
        preemption notice takes exactly this path — a preempted host
        is just a scale-down the scheduler didn't choose."""
        t0 = self.clock()
        eng = self._engines[i]
        self._active[i] = False
        self.scale_downs += 1
        shipped = 0
        try:
            for req in list(eng.scheduler.running):
                if not req.done and not req.prefilling and req.generated:
                    self._ship(i, req, exclude=(i,))
                    shipped += 1
            moved = self._harvest(eng)
            self._replay(i, moved, exclude=(i,), t0=t0, kind=reason,
                         error=error or RuntimeError(reason))
        except ServingUnavailable:
            self._active[i] = True    # nowhere to drain to: stay up
            raise
        self._shrink_mesh(i)
        self._scale_event(reason, i, shipped=shipped,
                          active=sum(self._active))

    def _shrink_mesh(self, lost_host):
        """Re-legalize an attached MeshPlan over the surviving hosts'
        device share (PR-15 ``shrink()``: dp drops to the largest
        fitting divisor, model axes fall back with TPU505 findings).
        Best-effort: serving correctness never depends on it."""
        plan = self.mesh_plan
        if plan is None:
            return None
        try:
            import numpy as _np
            devs = list(_np.asarray(plan.mesh.devices).flat)
            share = max(1, len(devs) // self.n_hosts)
            lost = set(id(d) for d in
                       devs[lost_host * share:(lost_host + 1) * share])
            surviving = [d for d in devs if id(d) not in lost]
            with obs.span("fabric:mesh_shrink", cat="recovery",
                          host=f"host{lost_host}",
                          survivors=len(surviving)):
                self.mesh_plan = plan.shrink(surviving)
            return self.mesh_plan
        except Exception as e:
            obs.instant("fabric.mesh_shrink_failed", cat="fault",
                        error=f"{type(e).__name__}: {e}"[:200])
            return None

    # -- results / streams -----------------------------------------------
    def _finish(self, req):
        self._results[req.id] = req

    def result(self, request_id):
        req = self._results[request_id]
        return list(req.prompt) + list(req.generated)

    def open_stream(self, request_id):
        kind, idx = self._owner[request_id]
        if kind == "fabric":
            for item in self._inflight:
                if item[0].envelope.request_id == request_id:
                    if item[3] is None:
                        from .streaming import TokenStream
                        item[3] = TokenStream(request_id)
                    return item[3]
            raise KeyError(request_id)
        return self._engines[idx].open_stream(request_id)

    def generate(self, prompts, **kwargs):
        ids = [self.add_request(p, **kwargs) for p in prompts]
        while self.has_unfinished():
            self.step()
        return [self.result(i) for i in ids]

    # -- bookkeeping -----------------------------------------------------
    def stats(self):
        per_host = {}
        total = {"tokens_generated": 0, "queue_depth": 0, "running": 0,
                 "blocks_in_use": 0}
        for i in range(self.n_hosts):
            if self._engines[i] is None:
                continue
            s = self._engines[i].stats()
            s["active"] = self._active[i]
            per_host[f"host{i}"] = s
            for k in ("tokens_generated", "queue_depth", "running",
                      "blocks_in_use"):
                total[k] += int(s.get(k, 0))
        ttfts = sorted(
            (r.t_first_token - r.t_submit) * 1e3
            for r in self._results.values()
            if r.t_first_token is not None and r.t_submit is not None)
        total["ttft_p99_ms"] = ttfts[
            min(len(ttfts) - 1, int(0.99 * len(ttfts)))] if ttfts \
            else 0.0
        degraded_ms = self.degraded_ms
        if self._degraded_mono is not None:
            degraded_ms += max(
                0.0, (self.clock() - self._degraded_mono) * 1e3)
        total.update({
            "hosts": self.n_hosts, "hosts_active": sum(self._active),
            "failovers": self.failovers, "replays": self.replays,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "preemptions": self.preemptions,
            "degraded": self.degraded,
            "degraded_ms": round(degraded_ms, 3),
            "degraded_events": self.degraded_events,
            "fenced_writes": self.fenced_writes,
            "store_epoch": self.store.epoch()
            if hasattr(self.store, "epoch") else None,
            "fabric_in_flight": len(self._inflight),
            "fabric_duplicates": getattr(self.transport,
                                         "duplicates", 0),
            "replica_health": {h.name: h.snapshot()
                               for h in self.health},
            "per_host": per_host,
        })
        return total

    def close(self):
        self._exit_degraded()   # flush an open outage window's span
        for eng in self._engines:
            if eng is not None:
                eng.close()
